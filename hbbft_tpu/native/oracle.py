"""ctypes loader for the C++ CPU oracle library."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB = os.path.join(_DIR, "libhbbft_native.so")

_oracle: Optional["NativeOracle"] = None


def _build() -> None:
    subprocess.run(
        ["make", "-s"], cwd=_DIR, check=True, capture_output=True, text=True
    )


class NativeOracle:
    """Thin typed wrapper over the C ABI in gf256.cpp / keccak.cpp."""

    def __init__(self):
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB)
            < max(
                os.path.getmtime(os.path.join(_DIR, f))
                for f in ("gf256.cpp", "keccak.cpp", "bls381.cpp",
                          "bls381_mont.S", "Makefile",
                          "gen_bls_constants.py",
                          os.path.join("..", "crypto", "bls12_381.py"))
            )
        ):
            _build()
        lib = ctypes.CDLL(_LIB)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.hbbft_gf_mul_bytes.argtypes = [u8p, u8p, u8p, ctypes.c_int64]
        lib.hbbft_gf_matmul.argtypes = [u8p, u8p, u8p] + [ctypes.c_int] * 3
        lib.hbbft_gf_matmul_simd.argtypes = [
            u8p, u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
        ]
        lib.hbbft_gf_invert.argtypes = [u8p, u8p, ctypes.c_int]
        lib.hbbft_gf_invert.restype = ctypes.c_int
        lib.hbbft_rs_matrix.argtypes = [ctypes.c_int, ctypes.c_int, u8p]
        lib.hbbft_rs_matrix.restype = ctypes.c_int
        lib.hbbft_rs_encode.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, u8p,
        ]
        lib.hbbft_rs_encode.restype = ctypes.c_int
        lib.hbbft_rs_reconstruct.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, u8p, u8p,
        ]
        lib.hbbft_rs_reconstruct.restype = ctypes.c_int
        lib.hbbft_keccak_f1600.argtypes = [u64p]
        lib.hbbft_sha3_256.argtypes = [u8p, ctypes.c_int64, u8p]
        lib.hbbft_sha3_256_batch.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, u8p,
        ]
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i = ctypes.c_int
        i64 = ctypes.c_int64
        i64p = ctypes.POINTER(ctypes.c_int64)
        for name, args, res in [
            ("bls_g1_add", [u8p, u8p, u8p], i),
            ("bls_g1_mul", [u8p, u8p, u8p], i),
            ("bls_g2_add", [u8p, u8p, u8p], i),
            ("bls_g2_mul", [u8p, u8p, u8p], i),
            ("bls_hash_g1", [u8p, i64, u8p], None),
            ("bls_hash_g2", [u8p, i64, u8p], None),
            ("bls_pairing_check", [u8p, u8p, i], i),
            ("bls_sign", [u8p, i64, u8p, u8p], None),
            ("bls_verify", [u8p, u8p, i64, u8p], i),
            ("bls_combine_g2", [u32p, u8p, i, u8p], i),
            ("bls_combine_g1", [u32p, u8p, i, u8p], i),
            ("bls_tpke_encrypt", [u8p, u8p, i64, u8p, u8p, u8p, u8p], i),
            ("bls_tpke_verify", [u8p, u8p, i64, u8p], i),
            ("bls_tpke_combine", [u32p, u8p, i, u8p, i64, u8p], i),
            ("bls_tpke_encrypt_batch", [u8p, u8p, i64p, i, u8p, u8p], i),
            ("bls_tpke_mask_batch", [u8p, u8p, i, u8p], i),
            ("bls_coin_batch", [u8p, u8p, i64p, i, u8p], i),
            ("bls_hash_g2_batch", [u8p, i64p, i, u8p], i),
            ("bls_g1_in_subgroup", [u8p], i),
            ("bls_g2_in_subgroup", [u8p], i),
            ("bls_tpke_decrypt_batch", [u8p, u8p, u8p, i64p, i, u8p], i),
            ("bls_tpke_check_decrypt_batch", [u8p, u8p, i64p, i, u8p], i),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        self._lib = lib

    @staticmethod
    def _p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

    def gf_mul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(a, dtype=np.uint8)
        b = np.ascontiguousarray(b, dtype=np.uint8)
        out = np.empty_like(a)
        self._lib.hbbft_gf_mul_bytes(self._p(a), self._p(b), self._p(out), a.size)
        return out

    def gf_matmul(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        A = np.ascontiguousarray(A, dtype=np.uint8)
        B = np.ascontiguousarray(B, dtype=np.uint8)
        r, k = A.shape
        k2, c = B.shape
        assert k == k2
        out = np.empty((r, c), dtype=np.uint8)
        self._lib.hbbft_gf_matmul(self._p(A), self._p(B), self._p(out), r, k, c)
        return out

    def gf_matmul_simd(
        self, A: np.ndarray, B: np.ndarray, out: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """SIMD constant-matrix apply (AVX2 pshufb nibble tables).

        Hot-path variant of :meth:`gf_matmul`: ``A`` is the small CACHED
        encode/decode matrix, ``B`` the shard rows; ``out`` may be a view
        into the caller's allocation (e.g. the parity tail of one
        contiguous shard buffer) so encode writes in place with no copy.
        """
        r, k = A.shape
        cols = int(B.shape[1])
        assert A.flags.c_contiguous and B.flags.c_contiguous
        if out is None:
            out = np.empty((r, cols), dtype=np.uint8)
        assert out.flags.c_contiguous and out.shape == (r, cols)
        self._lib.hbbft_gf_matmul_simd(
            self._p(A), self._p(B), self._p(out), r, k, cols
        )
        return out

    def gf_invert(self, M: np.ndarray) -> np.ndarray:
        M = np.ascontiguousarray(M, dtype=np.uint8)
        n = M.shape[0]
        out = np.empty((n, n), dtype=np.uint8)
        rc = self._lib.hbbft_gf_invert(self._p(M), self._p(out), n)
        if rc != 0:
            raise np.linalg.LinAlgError("singular")
        return out

    def rs_matrix(self, data: int, total: int) -> np.ndarray:
        out = np.empty((total, data), dtype=np.uint8)
        rc = self._lib.hbbft_rs_matrix(data, total, self._p(out))
        if rc != 0:
            raise ValueError("bad rs dims")
        return out

    def rs_encode(self, data_shards: np.ndarray, total: int) -> np.ndarray:
        data_shards = np.ascontiguousarray(data_shards, dtype=np.uint8)
        k, B = data_shards.shape
        shards = np.zeros((total, B), dtype=np.uint8)
        shards[:k] = data_shards
        rc = self._lib.hbbft_rs_encode(k, total, B, self._p(shards))
        if rc != 0:
            raise ValueError("encode failed")
        return shards

    def rs_reconstruct(
        self, data: int, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        total = len(shards)
        present = np.array(
            [1 if s is not None else 0 for s in shards], dtype=np.uint8
        )
        if int(present.sum()) < data:
            raise ValueError("too few shards")
        shard_len = len(next(s for s in shards if s is not None))
        buf = np.zeros((total, shard_len), dtype=np.uint8)
        for i, s in enumerate(shards):
            if s is not None:
                buf[i] = np.frombuffer(s, dtype=np.uint8)
        rc = self._lib.hbbft_rs_reconstruct(
            data, total, shard_len, self._p(buf), self._p(present)
        )
        if rc == -1:
            raise ValueError("too few shards")
        if rc != 0:
            raise ValueError("reconstruct failed")
        return [buf[i].tobytes() for i in range(total)]

    def keccak_f1600(self, state: np.ndarray) -> np.ndarray:
        state = np.ascontiguousarray(state, dtype=np.uint64).copy()
        assert state.shape == (25,)
        self._lib.hbbft_keccak_f1600(
            state.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
        )
        return state

    def sha3_256(self, data: bytes) -> bytes:
        arr = np.frombuffer(bytes(data), dtype=np.uint8)
        if arr.size == 0:
            arr = np.zeros(1, dtype=np.uint8)  # valid pointer; len passed as 0
        out = np.empty(32, dtype=np.uint8)
        self._lib.hbbft_sha3_256(self._p(arr), len(data), self._p(out))
        return out.tobytes()

    def sha3_256_batch(self, msgs: np.ndarray) -> np.ndarray:
        msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
        n, L = msgs.shape
        out = np.empty((n, 32), dtype=np.uint8)
        self._lib.hbbft_sha3_256_batch(self._p(msgs), n, L, self._p(out))
        return out


    # -- BLS12-381 full scheme (bls381.cpp) ---------------------------------
    # All points use the host serialization (G1: 97 bytes, G2: 193 bytes);
    # scalars are 32-byte big-endian.

    @staticmethod
    def _buf(n: int) -> np.ndarray:
        return np.zeros(n, dtype=np.uint8)

    @staticmethod
    def _arr(b: bytes) -> np.ndarray:
        return np.frombuffer(bytes(b), dtype=np.uint8)

    def bls_g1_add(self, a: bytes, b: bytes) -> bytes:
        out = self._buf(97)
        assert self._lib.bls_g1_add(self._p(self._arr(a)), self._p(self._arr(b)), self._p(out)) == 0
        return out.tobytes()

    def bls_g1_mul(self, a: bytes, k: int) -> bytes:
        out = self._buf(97)
        kb = self._arr(k.to_bytes(32, "big"))
        assert self._lib.bls_g1_mul(self._p(self._arr(a)), self._p(kb), self._p(out)) == 0
        return out.tobytes()

    def bls_g2_add(self, a: bytes, b: bytes) -> bytes:
        out = self._buf(193)
        assert self._lib.bls_g2_add(self._p(self._arr(a)), self._p(self._arr(b)), self._p(out)) == 0
        return out.tobytes()

    def bls_g2_mul(self, a: bytes, k: int) -> bytes:
        out = self._buf(193)
        kb = self._arr(k.to_bytes(32, "big"))
        assert self._lib.bls_g2_mul(self._p(self._arr(a)), self._p(kb), self._p(out)) == 0
        return out.tobytes()

    def bls_hash_g1(self, msg: bytes) -> bytes:
        out = self._buf(97)
        self._lib.bls_hash_g1(self._p(self._arr(msg or b"\0")), len(msg), self._p(out))
        return out.tobytes()

    def bls_hash_g2(self, msg: bytes) -> bytes:
        out = self._buf(193)
        self._lib.bls_hash_g2(self._p(self._arr(msg or b"\0")), len(msg), self._p(out))
        return out.tobytes()

    def bls_pairing_check(self, pairs) -> bool:
        n = len(pairs)
        g1s = np.concatenate([self._arr(p) for p, _ in pairs]) if n else self._buf(97)
        g2s = np.concatenate([self._arr(q) for _, q in pairs]) if n else self._buf(193)
        rc = self._lib.bls_pairing_check(self._p(g1s), self._p(g2s), n)
        assert rc >= 0
        return bool(rc)

    def bls_sign(self, msg: bytes, sk: int) -> bytes:
        out = self._buf(193)
        self._lib.bls_sign(
            self._p(self._arr(msg or b"\0")), len(msg),
            self._p(self._arr(sk.to_bytes(32, "big"))), self._p(out),
        )
        return out.tobytes()

    def bls_verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        rc = self._lib.bls_verify(
            self._p(self._arr(pk)), self._p(self._arr(msg or b"\0")),
            len(msg), self._p(self._arr(sig)),
        )
        assert rc >= 0
        return bool(rc)

    def _idx(self, indices):
        import ctypes

        arr = np.asarray(indices, dtype=np.uint32)
        return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))

    def bls_combine_g2(self, shares: dict) -> bytes:
        items = sorted(shares.items())
        keep, idxp = self._idx([i for i, _ in items])
        buf = np.concatenate([self._arr(s) for _, s in items])
        out = self._buf(193)
        assert self._lib.bls_combine_g2(idxp, self._p(buf), len(items), self._p(out)) == 0
        return out.tobytes()

    def bls_combine_g1(self, shares: dict) -> bytes:
        items = sorted(shares.items())
        keep, idxp = self._idx([i for i, _ in items])
        buf = np.concatenate([self._arr(s) for _, s in items])
        out = self._buf(97)
        assert self._lib.bls_combine_g1(idxp, self._p(buf), len(items), self._p(out)) == 0
        return out.tobytes()

    def bls_tpke_encrypt(self, pk: bytes, msg: bytes, r: int):
        u = self._buf(97)
        v = self._buf(max(len(msg), 1))
        w = self._buf(193)
        assert self._lib.bls_tpke_encrypt(
            self._p(self._arr(pk)), self._p(self._arr(msg or b"\0")),
            len(msg), self._p(self._arr(r.to_bytes(32, "big"))),
            self._p(u), self._p(v), self._p(w),
        ) == 0
        return u.tobytes(), v.tobytes()[: len(msg)], w.tobytes()

    def bls_tpke_verify(self, u: bytes, v: bytes, w: bytes) -> bool:
        rc = self._lib.bls_tpke_verify(
            self._p(self._arr(u)), self._p(self._arr(v or b"\0")),
            len(v), self._p(self._arr(w)),
        )
        assert rc >= 0
        return bool(rc)

    def bls_tpke_decrypt_share(self, u: bytes, sk: int) -> bytes:
        return self.bls_g1_mul(u, sk)

    def bls_tpke_combine(self, shares: dict, v: bytes) -> bytes:
        items = sorted(shares.items())
        keep, idxp = self._idx([i for i, _ in items])
        buf = np.concatenate([self._arr(s) for _, s in items])
        out = self._buf(max(len(v), 1))
        assert self._lib.bls_tpke_combine(
            idxp, self._p(buf), len(items),
            self._p(self._arr(v or b"\0")), len(v), self._p(out),
        ) == 0
        return out.tobytes()[: len(v)]

    def bls_tpke_encrypt_batch(self, pk: bytes, msgs, rs) -> list:
        """Encrypt many messages to one key in ONE native call (GIL released
        for the whole batch; fixed-base/window tables amortized inside).
        ``rs``: per-message scalars, byte-identical to per-item
        ``bls_tpke_encrypt`` with the same r.  Returns [(u, v, w)]."""
        lens = (ctypes.c_int64 * len(msgs))(*[len(m) for m in msgs])
        cat = self._arr(b"".join(msgs) or b"\0")
        rs_cat = self._arr(b"".join(r.to_bytes(32, "big") for r in rs))
        total = sum(290 + len(m) for m in msgs)
        out = self._buf(max(total, 1))
        assert self._lib.bls_tpke_encrypt_batch(
            self._p(self._arr(pk)), self._p(cat), lens, len(msgs),
            self._p(rs_cat), self._p(out),
        ) == 0
        res, off, ob = [], 0, out.tobytes()
        for m in msgs:
            res.append(
                (ob[off:off + 97], ob[off + 290:off + 290 + len(m)],
                 ob[off + 97:off + 290])
            )
            off += 290 + len(m)
        return res

    def bls_tpke_mask_batch(self, scalar: int, us) -> list:
        """[scalar]·U for each 97-byte U (the batched decrypt master-scalar
        fold) in one native call."""
        if not us:
            return []
        buf = np.concatenate([self._arr(u) for u in us])
        out = self._buf(97 * len(us))
        assert self._lib.bls_tpke_mask_batch(
            self._p(self._arr(scalar.to_bytes(32, "big"))),
            self._p(buf), len(us), self._p(out),
        ) == 0
        ob = out.tobytes()
        return [ob[i * 97:(i + 1) * 97] for i in range(len(us))]

    def bls_g1_in_subgroup(self, p: bytes) -> bool:
        rc = self._lib.bls_g1_in_subgroup(self._p(self._arr(p)))
        assert rc >= 0
        return bool(rc)

    def bls_g2_in_subgroup(self, p: bytes) -> bool:
        rc = self._lib.bls_g2_in_subgroup(self._p(self._arr(p)))
        assert rc >= 0
        return bool(rc)

    def bls_tpke_decrypt_batch(self, scalar: int, us, vs) -> list:
        """plaintexts[i] = vs[i] ⊕ KDF([scalar]·U_i) — the whole batched
        decrypt (GLV mask fold + KDF + XOR) in one native call."""
        if not us:
            return []
        ubuf = np.concatenate([self._arr(u) for u in us])
        vlens = (ctypes.c_int64 * len(vs))(*[len(v) for v in vs])
        vcat = self._arr(b"".join(vs) or b"\0")
        total = sum(len(v) for v in vs)
        out = self._buf(max(total, 1))
        # not inside an assert: under python -O a skipped call would return
        # silently-plausible all-zero plaintexts
        rc = self._lib.bls_tpke_decrypt_batch(
            self._p(self._arr(scalar.to_bytes(32, "big"))),
            self._p(ubuf), self._p(vcat), vlens, len(us), self._p(out),
        )
        if rc != 0:
            raise ValueError("bls_tpke_decrypt_batch failed (bad point?)")
        ob = out.tobytes()
        res, off = [], 0
        for v in vs:
            res.append(ob[off:off + len(v)])
            off += len(v)
        return res

    def bls_tpke_check_decrypt_batch(self, scalar: int, payloads):
        """Wire-validate (the full ``Ciphertext.from_bytes`` checks —
        canonical coordinates, on-curve, r-order subgroup for U and W) and
        decrypt many raw ciphertext payloads in ONE native call.  Returns
        the plaintext list, or None if some item failed validation (the
        caller re-parses per-item on the Python path for the precise
        error).  Payloads must be exact ``Ciphertext.to_bytes`` output
        (vlen == len − 294); hand anything else to the per-item path."""
        if not payloads:
            return []
        plens = (ctypes.c_int64 * len(payloads))(*[len(p) for p in payloads])
        cat = self._arr(b"".join(payloads))
        total = sum(len(p) - 294 for p in payloads)
        out = self._buf(max(total, 1))
        rc = self._lib.bls_tpke_check_decrypt_batch(
            self._p(self._arr(scalar.to_bytes(32, "big"))),
            self._p(cat), plens, len(payloads), self._p(out),
        )
        if rc != 0:
            return None
        ob = out.tobytes()
        res, off = [], 0
        for p in payloads:
            vlen = len(p) - 294
            res.append(ob[off:off + vlen])
            off += vlen
        return res

    def bls_hash_g2_batch(self, msgs) -> list:
        """H_G2(msg) for every message in ONE native call (GIL released;
        affine writes share one Fp2 inversion chain) — the host hash half
        of the split device encrypt.  Byte-identical to per-item
        ``bls_hash_g2``.  Returns 193-byte G2 encodings."""
        if not msgs:
            return []
        lens = (ctypes.c_int64 * len(msgs))(*[len(m) for m in msgs])
        cat = self._arr(b"".join(msgs) or b"\0")
        out = self._buf(193 * len(msgs))
        assert self._lib.bls_hash_g2_batch(
            self._p(cat), lens, len(msgs), self._p(out),
        ) == 0
        ob = out.tobytes()
        return [ob[i * 193:(i + 1) * 193] for i in range(len(msgs))]

    def bls_coin_batch(self, scalar: int, nonces) -> list:
        """parity(SHA3(g2_bytes([scalar]·H_G2(nonce)))) per nonce — a whole
        instance axis of common coins in one native call."""
        lens = (ctypes.c_int64 * len(nonces))(*[len(n) for n in nonces])
        cat = self._arr(b"".join(nonces) or b"\0")
        out = self._buf(max(len(nonces), 1))
        assert self._lib.bls_coin_batch(
            self._p(self._arr(scalar.to_bytes(32, "big"))),
            self._p(cat), lens, len(nonces), self._p(out),
        ) == 0
        return [bool(b) for b in out.tobytes()[: len(nonces)]]


def get_oracle() -> NativeOracle:
    """Build (if needed) and return the singleton oracle."""
    global _oracle
    if _oracle is None:
        _oracle = NativeOracle()
    return _oracle

// GF(2^8) / Reed-Solomon CPU oracle.
//
// Native (C++) ground-truth for the TPU kernels in hbbft_tpu/ops/{gf256,rs}.py,
// playing the role the `reed-solomon-erasure` crate plays for the reference's
// reliable broadcast (src/broadcast/broadcast.rs). Field: poly 0x11D, gen 2.
// Exposed via a C ABI and loaded with ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

struct Tables {
  uint8_t exp[512];
  int32_t log[256];
  Tables() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; ++i) exp[i] = exp[i - 255];
    log[0] = 0;
  }
};
const Tables T;

inline uint8_t gf_mul(uint8_t a, uint8_t b) {
  if (a == 0 || b == 0) return 0;
  return T.exp[T.log[a] + T.log[b]];
}

inline uint8_t gf_inv(uint8_t a) { return T.exp[255 - T.log[a]]; }

// out(rows x cols) = A(rows x k) * B(k x cols), row-major.
void matmul(const uint8_t* A, const uint8_t* B, uint8_t* out, int rows, int k,
            int cols) {
  std::memset(out, 0, static_cast<size_t>(rows) * cols);
  for (int i = 0; i < rows; ++i) {
    for (int j = 0; j < k; ++j) {
      uint8_t a = A[i * k + j];
      if (a == 0) continue;
      int la = T.log[a];
      const uint8_t* brow = B + static_cast<size_t>(j) * cols;
      uint8_t* orow = out + static_cast<size_t>(i) * cols;
      for (int c = 0; c < cols; ++c) {
        uint8_t b = brow[c];
        if (b) orow[c] ^= T.exp[la + T.log[b]];
      }
    }
  }
}

// Gauss-Jordan inverse; returns 0 on success, -1 if singular.
int invert(const uint8_t* M, uint8_t* out, int n) {
  std::vector<uint8_t> aug(static_cast<size_t>(n) * 2 * n, 0);
  for (int i = 0; i < n; ++i) {
    std::memcpy(&aug[static_cast<size_t>(i) * 2 * n], M + static_cast<size_t>(i) * n, n);
    aug[static_cast<size_t>(i) * 2 * n + n + i] = 1;
  }
  for (int col = 0; col < n; ++col) {
    int piv = -1;
    for (int r = col; r < n; ++r)
      if (aug[static_cast<size_t>(r) * 2 * n + col]) { piv = r; break; }
    if (piv < 0) return -1;
    if (piv != col)
      for (int c = 0; c < 2 * n; ++c)
        std::swap(aug[static_cast<size_t>(col) * 2 * n + c],
                  aug[static_cast<size_t>(piv) * 2 * n + c]);
    uint8_t inv = gf_inv(aug[static_cast<size_t>(col) * 2 * n + col]);
    for (int c = 0; c < 2 * n; ++c)
      aug[static_cast<size_t>(col) * 2 * n + c] =
          gf_mul(aug[static_cast<size_t>(col) * 2 * n + c], inv);
    for (int r = 0; r < n; ++r) {
      if (r == col) continue;
      uint8_t f = aug[static_cast<size_t>(r) * 2 * n + col];
      if (!f) continue;
      for (int c = 0; c < 2 * n; ++c)
        aug[static_cast<size_t>(r) * 2 * n + c] ^=
            gf_mul(f, aug[static_cast<size_t>(col) * 2 * n + c]);
    }
  }
  for (int i = 0; i < n; ++i)
    std::memcpy(out + static_cast<size_t>(i) * n,
                &aug[static_cast<size_t>(i) * 2 * n + n], n);
  return 0;
}

uint8_t gf_pow(uint8_t a, int e) {
  if (e == 0) return 1;
  if (a == 0) return 0;
  long long l = (static_cast<long long>(T.log[a]) * e) % 255;
  return T.exp[l];
}

// ---- SIMD constant-matrix apply -------------------------------------------
//
// The encode/decode matrices are tiny and fixed per (n, f) while the shard
// byte count is MB-scale, so the profitable shape is "constant scalar times
// long byte vector".  Each constant c gets a pair of 16-entry nibble tables
//   TLO[x] = c * x          (x in 0..15)
//   THI[x] = c * (x << 4)
// and gf_mul(c, b) == TLO[b & 15] ^ THI[b >> 4] — two pshufb lookups per 32
// bytes on AVX2 (the ISA-L trick).  Columns are walked in L2-sized tiles so
// every B row of a tile stays cache-hot across the k accumulation passes.

void build_nibble_tables(uint8_t c, uint8_t* tlo, uint8_t* thi) {
  for (int x = 0; x < 16; ++x) {
    tlo[x] = gf_mul(c, static_cast<uint8_t>(x));
    thi[x] = gf_mul(c, static_cast<uint8_t>(x << 4));
  }
}

// out(rows x cols) = A(rows x k) * B(k x cols), row-major; A is the small
// constant matrix, B/out are shard-length rows.
void matmul_simd(const uint8_t* A, const uint8_t* B, uint8_t* out, int rows,
                 int k, int64_t cols) {
  // nibble tables for every (row, j) constant, built once per call: the
  // matrix is rows*k bytes, the data is rows*k*cols — negligible setup.
  std::vector<uint8_t> tabs(static_cast<size_t>(rows) * k * 32);
  for (int i = 0; i < rows; ++i)
    for (int j = 0; j < k; ++j)
      build_nibble_tables(A[i * k + j],
                          &tabs[(static_cast<size_t>(i) * k + j) * 32],
                          &tabs[(static_cast<size_t>(i) * k + j) * 32 + 16]);
  const int64_t kTile = 1 << 16;  // 64 KiB column tile: k rows fit in L2
  for (int64_t t0 = 0; t0 < cols; t0 += kTile) {
    int64_t tlen = cols - t0 < kTile ? cols - t0 : kTile;
    for (int i = 0; i < rows; ++i) {
      uint8_t* orow = out + static_cast<size_t>(i) * cols + t0;
      std::memset(orow, 0, static_cast<size_t>(tlen));
      for (int j = 0; j < k; ++j) {
        const uint8_t a = A[i * k + j];
        if (a == 0) continue;
        const uint8_t* brow = B + static_cast<size_t>(j) * cols + t0;
        const uint8_t* tab = &tabs[(static_cast<size_t>(i) * k + j) * 32];
        int64_t c = 0;
#ifdef __AVX2__
        const __m128i tlo128 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(tab));
        const __m128i thi128 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(tab + 16));
        const __m256i tlo = _mm256_broadcastsi128_si256(tlo128);
        const __m256i thi = _mm256_broadcastsi128_si256(thi128);
        const __m256i mask = _mm256_set1_epi8(0x0F);
        for (; c + 32 <= tlen; c += 32) {
          __m256i x = _mm256_loadu_si256(
              reinterpret_cast<const __m256i*>(brow + c));
          __m256i lo = _mm256_and_si256(x, mask);
          __m256i hi = _mm256_and_si256(_mm256_srli_epi64(x, 4), mask);
          __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(tlo, lo),
                                          _mm256_shuffle_epi8(thi, hi));
          __m256i acc = _mm256_loadu_si256(
              reinterpret_cast<const __m256i*>(orow + c));
          _mm256_storeu_si256(reinterpret_cast<__m256i*>(orow + c),
                              _mm256_xor_si256(acc, prod));
        }
#endif
        const uint8_t* tlo8 = tab;
        const uint8_t* thi8 = tab + 16;
        for (; c < tlen; ++c)
          orow[c] ^= static_cast<uint8_t>(tlo8[brow[c] & 0x0F] ^
                                          thi8[brow[c] >> 4]);
      }
    }
  }
}

}  // namespace

extern "C" {

void hbbft_gf_mul_bytes(const uint8_t* a, const uint8_t* b, uint8_t* out,
                        int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = gf_mul(a[i], b[i]);
}

void hbbft_gf_matmul(const uint8_t* A, const uint8_t* B, uint8_t* out,
                     int rows, int k, int cols) {
  matmul(A, B, out, rows, k, cols);
}

// SIMD apply of a CALLER-CACHED matrix (encode parity block or decode
// inverse): unlike hbbft_rs_encode this never rebuilds the Vandermonde
// system per call, which is what made the old per-call path O(matrix) on
// top of O(bytes).  out must not alias B (parity tail vs data head of one
// allocation is fine).
void hbbft_gf_matmul_simd(const uint8_t* A, const uint8_t* B, uint8_t* out,
                          int rows, int k, int64_t cols) {
  matmul_simd(A, B, out, rows, k, cols);
}

int hbbft_gf_invert(const uint8_t* M, uint8_t* out, int n) {
  return invert(M, out, n);
}

// Systematic Vandermonde encode matrix, (total x data) row-major into out.
int hbbft_rs_matrix(int data, int total, uint8_t* out) {
  if (data < 1 || total < data || total > 256) return -1;
  std::vector<uint8_t> V(static_cast<size_t>(total) * data);
  for (int r = 0; r < total; ++r)
    for (int c = 0; c < data; ++c)
      V[static_cast<size_t>(r) * data + c] = gf_pow(static_cast<uint8_t>(r), c);
  std::vector<uint8_t> topinv(static_cast<size_t>(data) * data);
  if (invert(V.data(), topinv.data(), data) != 0) return -1;
  matmul(V.data(), topinv.data(), out, total, data, data);
  return 0;
}

// shards: (total x shard_len) row-major with data rows filled; fills parity.
int hbbft_rs_encode(int data, int total, int64_t shard_len, uint8_t* shards) {
  std::vector<uint8_t> M(static_cast<size_t>(total) * data);
  if (hbbft_rs_matrix(data, total, M.data()) != 0) return -1;
  matmul(M.data() + static_cast<size_t>(data) * data, shards,
         shards + static_cast<size_t>(data) * shard_len, total - data, data,
         static_cast<int>(shard_len));
  return 0;
}

// present: total flags; shards: (total x shard_len) with absent rows ignored.
// Reconstructs ALL rows in place. Returns 0 ok, -1 too few, -2 bad args.
int hbbft_rs_reconstruct(int data, int total, int64_t shard_len,
                         uint8_t* shards, const uint8_t* present) {
  if (data < 1 || total < data) return -2;
  std::vector<int> use;
  for (int i = 0; i < total && static_cast<int>(use.size()) < data; ++i)
    if (present[i]) use.push_back(i);
  if (static_cast<int>(use.size()) < data) return -1;
  std::vector<uint8_t> M(static_cast<size_t>(total) * data);
  if (hbbft_rs_matrix(data, total, M.data()) != 0) return -2;
  std::vector<uint8_t> sub(static_cast<size_t>(data) * data);
  std::vector<uint8_t> subshards(static_cast<size_t>(data) * shard_len);
  for (int i = 0; i < data; ++i) {
    std::memcpy(&sub[static_cast<size_t>(i) * data],
                &M[static_cast<size_t>(use[i]) * data], data);
    std::memcpy(&subshards[static_cast<size_t>(i) * shard_len],
                shards + static_cast<size_t>(use[i]) * shard_len, shard_len);
  }
  std::vector<uint8_t> dec(static_cast<size_t>(data) * data);
  if (invert(sub.data(), dec.data(), data) != 0) return -2;
  std::vector<uint8_t> recovered(static_cast<size_t>(data) * shard_len);
  matmul(dec.data(), subshards.data(), recovered.data(), data, data,
         static_cast<int>(shard_len));
  std::memcpy(shards, recovered.data(), recovered.size());
  // re-derive parity rows
  matmul(M.data() + static_cast<size_t>(data) * data, shards,
         shards + static_cast<size_t>(data) * shard_len, total - data, data,
         static_cast<int>(shard_len));
  return 0;
}

}  // extern "C"

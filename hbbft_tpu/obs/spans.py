"""Epoch-phase tracing: where did this epoch's latency go?

A :class:`SpanTracer` is a :class:`hbbft_tpu.traits.StepObserver` a driver
(``VirtualNet`` or ``NodeRuntime``) points at one node's message stream.  It
classifies every inbound consensus message by walking the wrapper chain the
protocols already encode (``HbWrap → SubsetWrap → BroadcastWrap → EchoMsg``
…) and aggregates, per ``(era, epoch)``, one span per phase:

- ``rbc_value`` / ``rbc_echo`` / ``rbc_ready`` — reliable-broadcast Value,
  Echo (incl. the EchoHash/CanDecode message-reduction variants), Ready;
- ``aba_bval`` / ``aba_aux`` / ``aba_conf`` / ``aba_coin`` / ``aba_term`` —
  binary agreement, one span **per ABA round** (the ``round`` field; Term
  is round-less);
- ``decrypt_share`` / ``decrypt_combine`` — threshold-decrypt share
  collection and the final interpolate+decode stretch (last share → batch);
- ``dkg_rotation`` — keyed per era: first signed Part/Ack observed → the
  batch that completes the change;
- ``epoch`` — the whole epoch, first phase activity → batch commit.

A span is ``[t_first, t_last]`` over the node's own monotonic clock plus a
message count; epochs are finalized when the driver reports a Step whose
output contains a committed batch.  Finished spans are retained bounded
(``max_spans``) and exportable as JSONL for offline analysis
(``bench.py --net`` turns them into the per-phase p50/p99 breakdown); phase
durations also feed the ``hbbft_phase_duration_seconds`` histogram so a live
``/metrics`` scrape answers the same question without the JSONL.

This is exactly the phase-attribution instrument "The Latency Price of
Threshold Cryptosystems in Blockchains" (PAPERS.md) builds ad hoc for its
measurements, kept always-on and per-node here.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.traits import Step, StepObserver

NodeId = Hashable

#: canonical protocol order of phases inside one epoch (export sort key —
#: observed t_first is the real ordering; this breaks exact ties)
PHASE_ORDER = (
    "rbc_value", "rbc_echo", "rbc_ready",
    "aba_bval", "aba_aux", "aba_conf", "aba_coin", "aba_term",
    "decrypt_share", "decrypt_combine",
    "dkg_rotation", "epoch",
)


def phase_group(name: str) -> str:
    """Coarse bucket for reporting: rbc / aba / coin / decrypt / dkg /
    epoch — ``bench.py --net`` and ``obs.top`` aggregate at this level."""
    if name.startswith("rbc_"):
        return "rbc"
    if name == "aba_coin":
        return "coin"
    if name.startswith("aba_"):
        return "aba"
    if name.startswith("decrypt_"):
        return "decrypt"
    if name.startswith("dkg_"):
        return "dkg"
    return name


@dataclass(frozen=True)
class Span:
    """One finished phase span of one epoch on one node."""

    node: str
    name: str
    era: int
    epoch: int
    round: Optional[int]
    t_start: float
    t_end: float
    count: int

    @property
    def duration_s(self) -> float:
        return self.t_end - self.t_start

    def as_dict(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "name": self.name,
            "era": self.era,
            "epoch": self.epoch,
            "round": self.round,
            "t_start": round(self.t_start, 6),
            "t_end": round(self.t_end, 6),
            "duration_s": round(self.duration_s, 6),
            "count": self.count,
        }


class _Agg:
    __slots__ = ("t_first", "t_last", "count")

    def __init__(self, t: float):
        self.t_first = t
        self.t_last = t
        self.count = 0

    def hit(self, t: float) -> None:
        if t < self.t_first:
            self.t_first = t
        if t > self.t_last:
            self.t_last = t
        self.count += 1


class SpanTracer(StepObserver):
    """Per-node epoch-phase tracer (see module docstring)."""

    def __init__(self, registry: Optional[Registry] = None,
                 node: Any = None, clock=time.perf_counter,
                 max_spans: int = 8192, max_open_epochs: int = 64):
        self.registry = registry or Registry()
        self.node = repr(node) if node is not None else "?"
        self.clock = clock
        self.finished: "deque[Span]" = deque(maxlen=max_spans)
        # (era, epoch) → (phase name, round) → _Agg.  Bounded two ways:
        # a straggler message for an ALREADY-FINALIZED epoch must not
        # re-open it (it could never finalize again), and a Byzantine
        # peer minting arbitrary future (era, epoch) values must not
        # grow this dict without limit — beyond max_open_epochs the
        # lowest key is evicted (and counted), never silently
        self.max_open_epochs = max_open_epochs
        self._open: Dict[Tuple[int, int], Dict[Tuple[str, Optional[int]],
                                               _Agg]] = {}
        self._done: "deque[Tuple[int, int]]" = deque(maxlen=256)
        self._done_set: set = set()
        self._dkg_open: Dict[int, _Agg] = {}
        self.epochs_finalized = 0
        # optional per-span finalization hook: called with each Span the
        # moment it is finished (the flight recorder journals them here;
        # `finished` stays the bounded in-memory view)
        self.sink: Optional[Any] = None
        r = self.registry
        self._h_phase = r.histogram(
            "hbbft_phase_duration_seconds",
            "wall-clock span of each consensus phase per epoch",
            labelnames=("phase",), max_label_sets=len(PHASE_ORDER) + 1,
        )
        self._c_msgs = r.counter(
            "hbbft_phase_messages_total",
            "inbound consensus messages classified per phase",
            labelnames=("phase",), max_label_sets=len(PHASE_ORDER) + 1,
        )
        # per-phase child handles: labels() costs a tuple build + dict
        # lookup per call, and on_message runs once per consensus message
        # — caching the children was part of recovering the r01→r02
        # sequential-throughput regression
        self._msg_children: Dict[str, Any] = {}
        self._h_epoch = r.histogram(
            "hbbft_node_epoch_duration_seconds",
            "first phase activity to batch commit, per epoch",
        )
        self._c_epochs = r.counter(
            "hbbft_node_epochs_total", "batches committed"
        )
        self._c_evicted = r.counter(
            "hbbft_phase_open_epochs_evicted_total",
            "open epoch traces dropped unfinalized (straggler re-opens "
            "or Byzantine epoch-key floods past max_open_epochs)"
        )

    # -- StepObserver --------------------------------------------------------

    def on_message(self, sender_id: NodeId, message: Any,
                   t: Optional[float] = None) -> None:
        hit = classify(message)
        if hit is None:
            return
        era, epoch, phase, rnd = hit
        now = self.clock() if t is None else t
        child = self._msg_children.get(phase)
        if child is None:
            child = self._msg_children[phase] = self._c_msgs.labels(
                phase=phase)
        child.inc()
        if phase == "dkg_rotation":
            agg = self._dkg_open.get(era)
            if agg is None:
                if not self._admit(self._dkg_open, era, cap=8):
                    return
                agg = self._dkg_open[era] = _Agg(now)
            agg.hit(now)
            return
        key = (era, epoch)
        if key in self._done_set:
            return  # straggler for a finalized epoch: don't re-open
        per_epoch = self._open.get(key)
        if per_epoch is None:
            if not self._admit(self._open, key,
                               cap=self.max_open_epochs):
                return
            per_epoch = self._open[key] = {}
        agg = per_epoch.get((phase, rnd))
        if agg is None:
            agg = per_epoch[(phase, rnd)] = _Agg(now)
        agg.hit(now)

    def _admit(self, open_map: Dict, key, cap: int) -> bool:
        """Bounded insert: at the cap, the HIGHEST key — epochs/eras only
        grow, so the highest open key is the most speculative and the
        attacker-minted flood is all high future keys — loses: either the
        newcomer is rejected outright or the highest existing entry is
        evicted.  Either way the genuine in-progress (lowest) trace
        survives a Byzantine epoch-key flood, and state stays ≤ cap."""
        if len(open_map) < cap:
            return True
        self._c_evicted.inc()
        highest = max(open_map)
        if key >= highest:
            return False  # the newcomer is the most speculative: drop it
        del open_map[highest]
        return True

    def on_step(self, step: Step, t: Optional[float] = None) -> None:
        for out in step.output:
            key = _batch_key(out)
            if key is None:
                continue
            era, epoch, change_complete = key
            now = self.clock() if t is None else t
            self._finalize_epoch(era, epoch, now)
            if change_complete:
                self._finalize_dkg(era, epoch, now)

    # -- finalization --------------------------------------------------------

    def _finalize_epoch(self, era: int, epoch: int, now: float) -> None:
        per_epoch = self._open.pop((era, epoch), None)
        if per_epoch is None:
            per_epoch = {}
        key = (era, epoch)
        if key not in self._done_set:
            if len(self._done) == self._done.maxlen:
                self._done_set.discard(self._done[0])
            self._done.append(key)
            self._done_set.add(key)
        spans: List[Span] = []
        t0_epoch = min(
            (a.t_first for a in per_epoch.values()), default=now
        )
        last_share: Optional[float] = None
        for (phase, rnd), agg in per_epoch.items():
            spans.append(Span(self.node, phase, era, epoch, rnd,
                              agg.t_first, agg.t_last, agg.count))
            if phase == "decrypt_share":
                last_share = agg.t_last
        if last_share is not None:
            # the combine (interpolate + decode) has no messages of its
            # own: it is the stretch from the last share to the commit
            spans.append(Span(self.node, "decrypt_combine", era, epoch,
                              None, last_share, now, 0))
        spans.append(Span(self.node, "epoch", era, epoch, None,
                          t0_epoch, now, sum(s.count for s in spans)))
        spans.sort(key=lambda s: (s.t_start, PHASE_ORDER.index(s.name)
                                  if s.name in PHASE_ORDER else 99,
                                  s.round or 0))
        for s in spans:
            self.finished.append(s)
            if s.name == "epoch":
                self._h_epoch.observe(s.duration_s)
            else:
                self._h_phase.labels(phase=s.name).observe(s.duration_s)
            if self.sink is not None:
                self.sink(s)
        self.epochs_finalized += 1
        self._c_epochs.inc()

    def _finalize_dkg(self, era: int, epoch: int, now: float) -> None:
        agg = self._dkg_open.pop(era, None)
        t0 = agg.t_first if agg is not None else now
        count = agg.count if agg is not None else 0
        s = Span(self.node, "dkg_rotation", era, epoch, None, t0, now,
                 count)
        self.finished.append(s)
        self._h_phase.labels(phase="dkg_rotation").observe(s.duration_s)
        if self.sink is not None:
            self.sink(s)

    # -- export --------------------------------------------------------------

    def spans_for(self, era: int, epoch: int) -> List[Span]:
        # list() first: exports run on the obs event loop while the pump's
        # worker thread finalizes epochs into the deque
        return [s for s in list(self.finished)
                if s.era == era and s.epoch == epoch]

    def export_jsonl(self) -> str:
        """One JSON object per finished span, in finalization order."""
        finished = list(self.finished)
        return "\n".join(
            json.dumps(s.as_dict()) for s in finished
        ) + ("\n" if finished else "")


# -- message classification --------------------------------------------------

# The protocol message types classify() dispatches on, resolved ONCE on
# first use: obs must stay importable without dragging protocol modules in
# at module-import time (tools and tests import obs alone), but re-running
# a dozen import statements per message was the dominant per-message cost
# the r01→r02 obs regression traced to.
_T = None


class _ClassifyTypes:
    __slots__ = (
        "AlgoMessage", "KeyGenWrap", "HbWrap", "DecryptionShareWrap",
        "SubsetWrap", "BroadcastWrap", "AgreementWrap", "ValueMsg",
        "EchoLike", "ReadyMsg", "BValMsg", "AuxMsg", "ConfMsg", "CoinMsg",
        "TermMsg",
    )

    def __init__(self):
        from hbbft_tpu.protocols.binary_agreement import (
            AuxMsg, BValMsg, CoinMsg, ConfMsg, TermMsg,
        )
        from hbbft_tpu.protocols.broadcast import (
            CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
        )
        from hbbft_tpu.protocols.dynamic_honey_badger import (
            HbWrap, KeyGenWrap,
        )
        from hbbft_tpu.protocols.honey_badger import (
            DecryptionShareWrap, SubsetWrap,
        )
        from hbbft_tpu.protocols.sender_queue import AlgoMessage
        from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap

        self.AlgoMessage = AlgoMessage
        self.KeyGenWrap = KeyGenWrap
        self.HbWrap = HbWrap
        self.DecryptionShareWrap = DecryptionShareWrap
        self.SubsetWrap = SubsetWrap
        self.BroadcastWrap = BroadcastWrap
        self.AgreementWrap = AgreementWrap
        self.ValueMsg = ValueMsg
        self.EchoLike = (EchoMsg, EchoHashMsg, CanDecodeMsg)
        self.ReadyMsg = ReadyMsg
        self.BValMsg = BValMsg
        self.AuxMsg = AuxMsg
        self.ConfMsg = ConfMsg
        self.CoinMsg = CoinMsg
        self.TermMsg = TermMsg


_classify_memo: Tuple[Any, Any] = (None, None)


def classify(message: Any
             ) -> Optional[Tuple[int, int, str, Optional[int]]]:
    """``(era, epoch, phase, round)`` for a consensus message, walking the
    wrapper chain; ``None`` for control traffic (EpochStarted, heartbeats)
    that belongs to no epoch phase."""
    global _T, _classify_memo
    # every inbound message is classified twice on the hot path (span
    # tracer + flight-journal epoch attribution), back to back with the
    # SAME object: a one-entry identity memo halves the wrapper walks
    memo_key, memo_hit = _classify_memo
    if memo_key is message:
        return memo_hit
    hit = _classify_walk(message)
    _classify_memo = (message, hit)
    return hit


def _classify_walk(message: Any
                   ) -> Optional[Tuple[int, int, str, Optional[int]]]:
    global _T
    T = _T
    if T is None:
        T = _T = _ClassifyTypes()
    era = 0
    if isinstance(message, T.AlgoMessage):
        message = message.msg
    if isinstance(message, T.KeyGenWrap):
        return (message.era, 0, "dkg_rotation", None)
    if isinstance(message, T.HbWrap):
        era = message.era
        message = message.msg
    if isinstance(message, T.DecryptionShareWrap):
        return (era, message.epoch, "decrypt_share", None)
    if not isinstance(message, T.SubsetWrap):
        return None
    epoch = message.epoch
    inner = message.msg
    if isinstance(inner, T.BroadcastWrap):
        m = inner.msg
        if isinstance(m, T.ValueMsg):
            return (era, epoch, "rbc_value", None)
        if isinstance(m, T.EchoLike):
            return (era, epoch, "rbc_echo", None)
        if isinstance(m, T.ReadyMsg):
            return (era, epoch, "rbc_ready", None)
        return None
    if isinstance(inner, T.AgreementWrap):
        m = inner.msg
        if isinstance(m, T.BValMsg):
            return (era, epoch, "aba_bval", m.epoch)
        if isinstance(m, T.AuxMsg):
            return (era, epoch, "aba_aux", m.epoch)
        if isinstance(m, T.ConfMsg):
            return (era, epoch, "aba_conf", m.epoch)
        if isinstance(m, T.CoinMsg):
            return (era, epoch, "aba_coin", m.epoch)
        if isinstance(m, T.TermMsg):
            return (era, epoch, "aba_term", None)
        return None
    return None


def _batch_key(out: Any) -> Optional[Tuple[int, int, bool]]:
    """``(era, epoch, change_completed)`` when ``out`` is a committed
    batch of any flavor, else None."""
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
    from hbbft_tpu.protocols.honey_badger import Batch as HbBatch
    from hbbft_tpu.protocols.queueing_honey_badger import QhbBatch
    from hbbft_tpu.protocols.vid import VidQhbBatch

    if isinstance(out, (QhbBatch, DhbBatch, VidQhbBatch)):
        complete = getattr(out.change, "state", None) == "complete"
        return (out.era, out.epoch, complete)
    if isinstance(out, HbBatch):
        return (0, out.epoch, False)
    return None

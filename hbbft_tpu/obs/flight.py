"""Black-box flight recorder: a bounded on-disk journal of protocol events.

Every node (real-socket ``NodeRuntime`` or deterministic ``VirtualNet``
sim) can append one record per protocol event to a per-node **journal**:
inbound/outbound consensus messages (with sender/target, (era, epoch) and
the full wire payload), batch commits carrying the ledger-digest chain
head, every ``FaultLog`` entry, span finalizations from
:class:`~hbbft_tpu.obs.spans.SpanTracer`, and lifecycle notes (start /
restart / replay-gap / crash / stop).  The journal is what the forensic
auditor (:mod:`hbbft_tpu.obs.audit`) merges across nodes to reconstruct
*what happened, in what order, on whom* after a fork, stall, or slashing
— the offline sibling of the live ``/metrics`` endpoint, in the spirit of
Thetacrypt's per-node event records (PAPERS.md).

On-disk format (byte-deterministic given a deterministic run):

- a journal is a directory of **segment files** ``seg-IIII-NNNNNN.fjl``
  (``IIII`` = incarnation, bumped every process (re)start; ``NNNNNN`` =
  rotation index).  Segments rotate at ``max_segment_bytes`` and the
  oldest are deleted beyond ``max_segments`` — the recorder is bounded;
- each segment is a sequence of framed records:
  ``u32 length | u32 crc32(payload) | payload`` where ``payload`` is the
  :func:`hbbft_tpu.protocols.wire.encode_message` bytes of one of the
  ``Flight*`` record dataclasses below — journal records are registered
  with the wire codec like any other protocol message, so the
  wire-completeness checker and the per-type hash/round-trip regression
  cover the durable format;
- every segment begins with a :class:`FlightHello` so any single file
  self-describes its node/flavor/incarnation;
- a torn tail (mid-record truncation after a crash) is skipped loudly:
  the reader stops the segment, counts
  ``hbbft_obs_flight_torn_tails_total``, and never raises.

The recorder's own failure paths are accounted, never silent: a disk
error counts ``hbbft_obs_flight_write_failures_total`` (hblint's
``fault-accounting`` scope covers ``obs/``), an unencodable message
counts ``hbbft_obs_flight_encode_skips_total``.

Timestamps: ``clock=None`` (the ``VirtualNet`` default) stamps records
with a **logical clock** — the record sequence number — so two runs of
the same deterministic schedule produce byte-identical journals; the
networked runtime passes a real clock for cross-node forensics.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import struct
import zlib
from collections import deque
from dataclasses import dataclass, fields
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.obs.metrics import DEFAULT, Registry
from hbbft_tpu.obs.trace import (
    STAGE_HOPS, FlightTrace, iter_tids, pack_tids, trace_id,
)
from hbbft_tpu.protocols import wire
from hbbft_tpu.traits import Step, StepObserver

logger = logging.getLogger("hbbft_tpu.obs")

#: (era, epoch) recorded for control traffic that belongs to no epoch
#: (heartbeat-adjacent runtime messages, unclassifiable payloads) —
#: sorts after every real epoch in the audit timeline
UNKNOWN_EPOCH = (1 << 64) - 1

_SEGMENT_RE = re.compile(r"^seg-(\d{4})-(\d{6})\.fjl$")
_FRAME_HEADER = struct.Struct(">II")


def _max_record_bytes() -> int:
    """Reader-side sanity cap on a single record's claimed length —
    larger claims are treated as corruption (torn tail), not allocated.
    Resolved at call time because the wire caps are documented as
    deployment-raisable module knobs; a journal written under a raised
    cap must read back under the same setting."""
    return wire.MAX_MESSAGE_BYTES + 4096


# ===========================================================================
# Journal record types (wire-registered — see wire._lazy_register 0x80-0x85)
# ===========================================================================


@dataclass(frozen=True)
class FlightHello:
    """Segment header: which node wrote this journal, and its lifecycle
    incarnation (bumped per process start — restarts are visible)."""

    node: str
    flavor: str          # "runtime" (sockets) | "virtualnet" (sim)
    incarnation: int
    seq: int
    t: float


@dataclass(frozen=True)
class FlightMsg:
    """One consensus message crossing this node's boundary."""

    seq: int
    t: float
    direction: str       # "in" | "out"
    peer: str            # in: repr(sender); out: target descriptor
    era: int
    epoch: int
    mtype: str           # message class name
    payload: bytes       # wire.encode_message bytes (b"" if unencodable)


@dataclass(frozen=True)
class FlightCommit:
    """A committed batch: the ledger-digest chain head after folding it."""

    seq: int
    t: float
    era: int
    epoch: int
    index: int           # position in the digest chain (0-based)
    digest: bytes        # chain head after this batch (32 bytes)


@dataclass(frozen=True)
class FlightFault:
    """One FaultLog entry: ``node`` did ``kind`` (FaultKind name).

    ``(era, epoch)`` is the key of the inbound message being handled
    when the fault was logged (:data:`UNKNOWN_EPOCH` for faults raised
    outside message handling, e.g. on local input) — it places the
    evidence inside its epoch on the audit timeline."""

    seq: int
    t: float
    node: str
    kind: str
    era: int
    epoch: int


@dataclass(frozen=True)
class FlightSpan:
    """A finalized epoch-phase span (see obs.spans.Span)."""

    seq: int
    t: float
    name: str
    era: int
    epoch: int
    round: Optional[int]
    t_start: float
    t_end: float
    count: int


@dataclass(frozen=True)
class FlightNote:
    """Lifecycle event: start / restart / replay_gap / crash / stop."""

    seq: int
    t: float
    kind: str
    detail: str


@dataclass(frozen=True)
class HealthIncident:
    """One classified live-health finding (the health plane's record).

    Emitted by the watchtower (:mod:`hbbft_tpu.obs.watch`) into its own
    journal, and by a node's runtime at local health transitions, so the
    online detection trail is as durable and auditable as the protocol
    evidence it points at.  ``key`` is the stable dedup identity: one
    underlying fault yields ONE incident even across poll ticks, and a
    replayed journal re-yields the identical key."""

    seq: int
    t: float
    source: str          # who raised it: "watchtower" or a node id
    kind: str            # classification: equivocation / straggler / …
    severity: str        # "info" | "warn" | "fault" | "fork"
    subject: str         # the implicated node / peer / rule subject
    key: str             # stable dedup identity of the finding
    detail: str


@dataclass(frozen=True)
class PerfSnapshot:
    """One performance-plane sampling window (the perf plane's record).

    Journaled every N-th sample by :class:`hbbft_tpu.obs.perf.PerfPlane`
    so post-hoc forensics can line capacity history up against the
    fault/commit timeline.  ``doc`` is the JSON-encoded per-layer
    utilization + per-segment breakdown (a string, not a dict: flight
    records must stay hashable for the wire-completeness contract)."""

    seq: int
    t: float
    source: str          # the sampling node (recorder identity)
    window_s: float      # wall seconds covered by this window
    cpu_frac: float      # whole-process CPU fraction over the window
    headroom: float      # 1 - max layer utilization (the slack scalar)
    doc: str             # JSON: {"layers": {...}, "segments": {...}}


RECORD_TYPES = (FlightHello, FlightMsg, FlightCommit, FlightFault,
                FlightSpan, FlightNote, FlightTrace, HealthIncident,
                PerfSnapshot)


def record_as_dict(rec: Any) -> Dict[str, Any]:
    """JSON-safe dict view of a record (``/flight`` tail + audit JSON):
    message payloads are summarized as digest+size, not inlined."""
    out: Dict[str, Any] = {"type": type(rec).__name__}
    for f in fields(rec):
        v = getattr(rec, f.name)
        if isinstance(v, bytes):
            out[f.name + "_sha3"] = hashlib.sha3_256(v).hexdigest()[:16]
            out[f.name + "_bytes"] = len(v)
        else:
            out[f.name] = v
    if isinstance(rec, FlightTrace):
        # trace ids are identifiers, not payloads — show them outright
        # so ``/trace`` output can be grepped by tid
        out["tids"] = [t.hex() for t in iter_tids(rec.tids)]
    return out


# ===========================================================================
# Classification helpers
# ===========================================================================


def message_epoch(msg: Any) -> Tuple[int, int]:
    """The (era, epoch) a message belongs to, via the span classifier;
    :data:`UNKNOWN_EPOCH` for control traffic."""
    from hbbft_tpu.obs.spans import classify
    from hbbft_tpu.protocols.sender_queue import AlgoMessage, EpochStarted

    if isinstance(msg, EpochStarted):
        return msg.key
    hit = classify(msg)
    if hit is not None:
        return (hit[0], hit[1])
    if isinstance(msg, AlgoMessage):
        # classify() unwraps AlgoMessage itself; reaching here means the
        # inner message is control/unknown too
        return (0, UNKNOWN_EPOCH)
    return (0, UNKNOWN_EPOCH)


def target_str(target: Any) -> str:
    """Deterministic descriptor of a :class:`~hbbft_tpu.traits.Target`
    (``all`` / ``nodes:1,3`` / ``all_except:0``), used as the ``peer``
    field of outbound records — the auditor checks a receive's node
    against it when matching sends to receives."""
    ids = ",".join(sorted((repr(i) for i in target.ids or ()), key=str))
    if target.kind == target.ALL:
        return "all"
    if target.kind == target.ALL_EXCEPT:
        return f"all_except:{ids}"
    return f"nodes:{ids}"


def target_covers(peer_field: str, node: str) -> bool:
    """Does an outbound record's target descriptor include ``node``
    (a repr'd node id)?"""
    if peer_field == "all":
        return True
    kind, _, ids = peer_field.partition(":")
    members = set(ids.split(",")) if ids else set()
    if kind == "all_except":
        return node not in members
    return node in members


# ===========================================================================
# Recorder
# ===========================================================================


class FlightRecorder:
    """Append-only segment-rotated journal writer for ONE node.

    Thread-unsafe by design (one owner: the node's event loop / the sim's
    crank loop).  Every append is flushed so a SIGKILL loses at most the
    record being written — which the reader then skips as a torn tail.
    """

    def __init__(self, dirpath: str, node: str, *, flavor: str = "runtime",
                 clock: Optional[Callable[[], float]] = None,
                 max_segment_bytes: int = 4 * 2**20,
                 max_segments: int = 16,
                 registry: Optional[Registry] = None,
                 tail_records: int = 512):
        self.dirpath = dirpath
        self.node = node
        self.flavor = flavor
        self.clock = clock
        self.max_segment_bytes = max_segment_bytes
        self.max_segments = max_segments
        # raw records, dict-ified lazily at read time: the tail is read
        # rarely (``/flight`` tails, audits) but appended on EVERY hot-path
        # message — eager record_as_dict (a sha3 per payload) was ~5% of
        # node CPU under load
        self.tail: "deque[Any]" = deque(maxlen=tail_records)
        self._seq = 0
        self._fh = None
        self._seg_bytes = 0
        self._seg_idx = 0
        self.closed = False
        r = registry if registry is not None else Registry()
        self.registry = r
        self._c_records = r.counter(
            "hbbft_obs_flight_records_total",
            "journal records appended, by record type",
            labelnames=("type",), max_label_sets=len(RECORD_TYPES) + 1)
        # pre-resolved per-type children: .labels() re-validates the
        # label set on every call, and _append is per-message hot
        self._rec_counters = {
            cls.__name__: self._c_records.labels(type=cls.__name__)
            for cls in RECORD_TYPES
        }
        self._c_bytes = r.counter(
            "hbbft_obs_flight_bytes_total",
            "journal bytes appended (framing included)")
        self._c_write_fail = r.counter(
            "hbbft_obs_flight_write_failures_total",
            "journal records lost to disk errors (open/write/flush)")
        self._c_encode_skip = r.counter(
            "hbbft_obs_flight_encode_skips_total",
            "messages journaled without payload (no wire encoding)")
        self._c_rotations = r.counter(
            "hbbft_obs_flight_rotations_total",
            "segment rotations (size cap reached)")
        self._c_truncations = r.counter(
            "hbbft_obs_flight_truncations_total",
            "journal segments deleted at digest-chain checkpoints "
            "(bounded storage; the chain head covers the history)")
        self._c_prior_indexed = r.counter(
            "hbbft_obs_flight_prior_segments_indexed_total",
            "older-incarnation segments whose commit range was indexed "
            "at startup so checkpoint truncation can reason about them "
            "across restarts")
        self._g_segments = r.gauge(
            "hbbft_obs_flight_segments",
            "journal segment files currently retained on disk")
        # highest commit-chain index per retained segment.  Segments of
        # THIS incarnation are tracked as they rotate; segments left by
        # OLDER incarnations are indexed once at startup (below) so the
        # digest-chain checkpoint truncation can retire them too — an
        # audit across restarts must not silently lose the incident
        # window, and a restart must not pin stale segments forever.
        # Older segments with no commits (or unreadable ones) stay
        # unindexed and are KEPT: the max_segments cap remains their
        # only bound, which errs on the side of preserving forensics.
        self._seg_commit_high: Dict[str, int] = {}
        self._cur_commit_high = -1
        os.makedirs(dirpath, exist_ok=True)
        self.incarnation = self._next_incarnation()
        self._index_prior_segments()
        self._open_segment()
        self.note("restart" if self.incarnation > 1 else "start",
                  f"flavor={flavor}")

    # -- lifecycle -----------------------------------------------------------

    def _next_incarnation(self) -> int:
        prev = [inc for inc, _idx, _name in self._segments()]
        return (max(prev) + 1) if prev else 1

    def _segments(self) -> List[Tuple[int, int, str]]:
        """Sorted (incarnation, index, filename) of on-disk segments."""
        out = []
        try:
            names = os.listdir(self.dirpath)
        except OSError:
            self._c_write_fail.inc()
            return []
        for name in names:
            m = _SEGMENT_RE.match(name)
            if m:
                out.append((int(m.group(1)), int(m.group(2)), name))
        return sorted(out)

    def _index_prior_segments(self) -> None:
        """Best-effort scan of older incarnations' on-disk segments for
        their highest commit index (journal-spanning retention): each
        indexed segment becomes eligible for checkpoint truncation once
        the chain passes it.  The scan is lenient on purpose — a torn
        tail still yields the commits before the tear (uncounted here:
        the audit reader is the loud pass), and an unreadable segment
        is simply kept."""
        for _inc, _idx, name in self._segments():
            try:
                with open(os.path.join(self.dirpath, name), "rb") as fh:
                    data = fh.read()
            # hblint: disable=fault-swallowed-drop (nothing dropped: an
            # unreadable prior segment stays on disk unindexed — kept,
            # not lost; the audit reader surfaces the damage loudly)
            except OSError:
                continue
            records, _torn = read_segment_bytes(data, count_torn=False)
            high = max((r.index for r in records
                        if isinstance(r, FlightCommit)), default=-1)
            if high >= 0:
                self._seg_commit_high[name] = high
                self._c_prior_indexed.inc()

    def _open_segment(self) -> None:
        name = f"seg-{self.incarnation:04d}-{self._seg_idx:06d}.fjl"
        self._seg_name = name
        self._cur_commit_high = -1
        try:
            self._fh = open(os.path.join(self.dirpath, name), "wb")
        except OSError as exc:
            self._fh = None
            self._c_write_fail.inc()
            logger.error("flight: cannot open segment %s: %s", name, exc)
        self._seg_bytes = 0
        self._seg_records = 0
        self._g_segments.set(len(self._segments()))
        # every segment self-describes (a lone rotated file still names
        # its node/incarnation)
        self._append(FlightHello(self.node, self.flavor, self.incarnation,
                                 self._next_seq(), 0.0))

    def _rotate(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                self._c_write_fail.inc()
        if self._cur_commit_high >= 0:
            self._seg_commit_high[self._seg_name] = self._cur_commit_high
        self._seg_idx += 1
        self._c_rotations.inc()
        segs = self._segments()
        while len(segs) >= self.max_segments:
            inc, idx, name = segs.pop(0)
            try:
                os.remove(os.path.join(self.dirpath, name))
            except OSError:
                self._c_write_fail.inc()
            # keep the checkpoint map in step with the disk, or
            # truncate_checkpoint would retry the missing file forever
            self._seg_commit_high.pop(name, None)
        self._open_segment()

    def close(self) -> None:
        if self.closed:
            return
        self.note("stop", "")
        self.closed = True
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                self._c_write_fail.inc()
            self._fh = None

    def flush(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                self._c_write_fail.inc()

    # -- appends -------------------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _now(self, t: Optional[float] = None) -> float:
        # an explicit t wins (the drivers pass the event's own time —
        # the virtual clock under the sim, so determinism holds; the
        # capture-site clock under sockets, so the journal timestamp is
        # the event, not the append); otherwise the logical clock (the
        # NEXT record's seq) or the recorder's clock
        if t is not None:
            return t
        return float(self._seq + 1) if self.clock is None else self.clock()

    def _append(self, rec: Any) -> None:
        payload = wire.encode_message(rec)
        frame = _FRAME_HEADER.pack(len(payload),
                                   zlib.crc32(payload)) + payload
        if self._fh is not None:
            try:
                self._fh.write(frame)
                self._fh.flush()
            except (OSError, ValueError):
                self._c_write_fail.inc()
        else:
            self._c_write_fail.inc()
        self._rec_counters[type(rec).__name__].inc()
        self._c_bytes.inc(len(frame))
        # small records (the per-message hot path) go in raw and are
        # dict-ified only when the tail is read; big ones (MB-scale RBC
        # Value payloads) are summarized NOW so the tail can never pin
        # hundreds of MB of payload bytes — 512 × 4 KiB caps it at ~2 MB
        self.tail.append(rec if len(frame) <= 4096 else
                         record_as_dict(rec))
        self._seg_bytes += len(frame)
        self._seg_records += 1
        # > 1: the segment-header hello alone must never trigger a rotate
        # (a pathologically small cap would otherwise recurse forever)
        if self._seg_bytes >= self.max_segment_bytes and \
                self._seg_records > 1:
            self._rotate()

    def record_msg(self, direction: str, peer: str, message: Any,
                   t: Optional[float] = None,
                   payload: Optional[bytes] = None) -> None:
        # the receive path already HAS the wire payload it decoded the
        # message from — callers pass it to skip a re-encode per message
        if payload is None:
            try:
                payload = wire.encode_message(message)
            except TypeError:
                self._c_encode_skip.inc()
                payload = b""
        era, epoch = message_epoch(message)
        self._append(FlightMsg(self._next_seq(), self._now(t), direction,
                               peer, era, epoch, type(message).__name__,
                               payload))

    def record_commit(self, era: int, epoch: int, index: int,
                      digest: bytes, t: Optional[float] = None) -> None:
        self._append(FlightCommit(self._next_seq(), self._now(t), era,
                                  epoch, index, digest))
        if index > self._cur_commit_high:
            self._cur_commit_high = index
        self.flush()  # a commit is the record worth surviving a crash

    def truncate_checkpoint(self, min_index: int) -> int:
        """Bounded storage: delete rotated segments — of this
        incarnation AND of older incarnations indexed at startup —
        whose every commit lies below digest-chain index ``min_index``;
        the checkpointed chain (head + ``/status``) covers them.
        Older-incarnation segments that could not be indexed (no
        commits, unreadable) are kept.  The current segment is never
        deleted.  Returns how many segments were removed (each
        counted)."""
        if min_index <= 0:
            return 0
        removed = 0
        for name in sorted(self._seg_commit_high):
            if self._seg_commit_high[name] >= min_index:
                continue
            try:
                os.remove(os.path.join(self.dirpath, name))
            # hblint: disable=fault-swallowed-drop (nothing dropped: the
            # segment is already gone — the max_segments cap beat this
            # checkpoint to it; counting it as a write failure would
            # fake a disk-health signal)
            except FileNotFoundError:
                del self._seg_commit_high[name]
                continue
            except OSError:
                self._c_write_fail.inc()
                continue
            del self._seg_commit_high[name]
            removed += 1
            self._c_truncations.inc()
        if removed:
            self._g_segments.set(len(self._segments()))
        return removed

    def record_fault(self, node: str, kind: str, era: int = 0,
                     epoch: int = UNKNOWN_EPOCH,
                     t: Optional[float] = None) -> None:
        self._append(FlightFault(self._next_seq(), self._now(t), node,
                                 kind, era, epoch))

    def record_trace(self, stage: str, era: int, epoch: int, tids: bytes,
                     detail: str = "", t: Optional[float] = None) -> None:
        """One causal stage crossing (see :mod:`hbbft_tpu.obs.trace`):
        ``tids`` is the concatenated 16-byte trace-id vector of every tx
        crossing ``stage`` together — one record per batch, not per tx."""
        self._append(FlightTrace(self._next_seq(), self._now(t), stage,
                                 era, epoch, STAGE_HOPS.get(stage, 0),
                                 detail, tids))

    def record_span(self, span: Any) -> None:
        """Sink for :attr:`hbbft_tpu.obs.spans.SpanTracer.sink`."""
        self._append(FlightSpan(self._next_seq(), self._now(), span.name,
                                span.era, span.epoch, span.round,
                                span.t_start, span.t_end, span.count))

    def note(self, kind: str, detail: str) -> None:
        self._append(FlightNote(self._next_seq(), self._now(), kind,
                                detail))
        if kind in ("crash", "replay_gap"):
            self.flush()

    def record_incident(self, kind: str, severity: str, subject: str,
                        key: str, detail: str,
                        t: Optional[float] = None) -> None:
        """One classified health finding (see :class:`HealthIncident`);
        flushed immediately — an incident is exactly the record an
        operator reads the journal for after a crash."""
        self._append(HealthIncident(self._next_seq(), self._now(t),
                                    self.node, kind, severity, subject,
                                    key, detail))
        self.flush()

    def record_perf(self, window_s: float, cpu_frac: float,
                    headroom: float, doc: str,
                    t: Optional[float] = None) -> None:
        """One perf-plane sampling window (see :class:`PerfSnapshot`);
        not flushed eagerly — perf history is valuable but never worth a
        sync on the pump path (crash flush picks up the tail)."""
        self._append(PerfSnapshot(self._next_seq(), self._now(t),
                                  self.node, float(window_s),
                                  float(cpu_frac), float(headroom), doc))

    # -- introspection -------------------------------------------------------

    def stats_doc(self) -> Dict[str, Any]:
        return {
            "dir": self.dirpath,
            "incarnation": self.incarnation,
            "records": int(self._c_records.total()),
            "bytes": int(self._c_bytes.value()),
            "segments": len(self._segments()),
            "truncations": int(self._c_truncations.value()),
            "write_failures": int(self._c_write_fail.value()),
        }

    def tail_jsonl(self) -> str:
        """Recent records as JSONL — the ``/flight`` endpoint body."""
        return "\n".join(
            json.dumps(r if isinstance(r, dict) else record_as_dict(r))
            for r in self.tail) + ("\n" if self.tail else "")

    def trace_jsonl(self) -> str:
        """The tail's FlightTrace records only — the ``/trace``
        endpoint body (per-tx causal stages, tids in hex)."""
        rows = []
        for r in self.tail:
            if isinstance(r, FlightTrace):
                rows.append(record_as_dict(r))
            elif isinstance(r, dict) and r["type"] == "FlightTrace":
                rows.append(r)
        return "\n".join(json.dumps(d) for d in rows) + (
            "\n" if rows else "")


# ===========================================================================
# Observer: StepObserver events → journal records
# ===========================================================================


class FlightObserver(StepObserver):
    """Translate the driver-side observer hook into journal records.

    Maintains its own ledger-digest chain over committed batches (the
    same :func:`hbbft_tpu.protocols.wire.batch_bytes` canonicalization
    ``NodeRuntime`` uses) so both drivers journal the identical chain.
    An optional inner :class:`~hbbft_tpu.obs.spans.SpanTracer` is driven
    through the same hook and its finalized spans are journaled via the
    tracer's ``sink`` (the ``VirtualNet`` composition; ``NodeRuntime``
    drives its own tracer and wires the sink itself).
    """

    def __init__(self, recorder: FlightRecorder, spans: Any = None):
        self.recorder = recorder
        self.spans = spans
        if spans is not None:
            spans.sink = self.record_span
        self._ledger = b"\x00" * 32
        self._chain_len = 0
        self._last_key = (0, UNKNOWN_EPOCH)

    def seed_chain(self, head: bytes, chain_len: int) -> None:
        """Snapshot state-sync activation: continue the digest chain
        from an era boundary instead of genesis, so this journal's
        commit indices line up with the donors' (the auditor verifies
        the boundary against the accompanying ``statesync`` note)."""
        self._ledger = bytes(head)
        self._chain_len = int(chain_len)

    # -- StepObserver --------------------------------------------------------

    def on_message(self, sender_id: Any, message: Any,
                   t: Optional[float] = None,
                   payload: Optional[bytes] = None) -> None:
        if self.spans is not None:
            self.spans.on_message(sender_id, message, t)
        self._last_key = message_epoch(message)
        self.recorder.record_msg("in", repr(sender_id), message, t=t,
                                 payload=payload)

    def on_input(self, sender_id: Any, inp: Any,
                 t: Optional[float] = None) -> None:
        # locally-admitted contribution: journal the ingress stage of
        # every tx it carries so the critical path starts on this node
        # even without a socket client (the VirtualNet composition;
        # NodeRuntime journals ingress itself at mempool admission)
        tx = getattr(inp, "tx", None)
        if isinstance(tx, (bytes, bytearray)):
            self.recorder.record_trace("ingress", 0, UNKNOWN_EPOCH,
                                       trace_id(bytes(tx)),
                                       detail=repr(sender_id), t=t)

    def on_step(self, step: Step, t: Optional[float] = None) -> None:
        from hbbft_tpu.obs.spans import _batch_key

        if self.spans is not None:
            self.spans.on_step(step, t)  # finalized spans → sink
        for fault in step.fault_log:
            # a Step's faults arose while handling the last inbound
            # message: its (era, epoch) places the evidence on the
            # timeline (UNKNOWN_EPOCH for input-driven steps)
            self.recorder.record_fault(repr(fault.node_id),
                                       fault.kind.name,
                                       *self._last_key, t=t)
        for out in step.output:
            key = _batch_key(out)
            if key is None:
                continue
            era, epoch, _complete = key
            all_txs = getattr(out, "all_txs", None)
            if all_txs is not None:
                tids = pack_tids(trace_id(tx) for tx in all_txs())
                if tids:
                    self.recorder.record_trace("commit", era, epoch,
                                               tids, t=t)
            self._ledger = hashlib.sha3_256(
                self._ledger + wire.batch_bytes(out)).digest()
            self.recorder.record_commit(era, epoch, self._chain_len,
                                        self._ledger, t=t)
            self._chain_len += 1
        for tm in step.messages:
            self.recorder.record_msg("out", target_str(tm.target),
                                     tm.message, t=t)

    def on_note(self, kind: str, detail: str,
                t: Optional[float] = None) -> None:
        self.recorder.note(kind, detail)

    # -- plumbing ------------------------------------------------------------

    def record_span(self, span: Any) -> None:
        self.recorder.record_span(span)

    @property
    def chain_head(self) -> bytes:
        return self._ledger

    @property
    def chain_len(self) -> int:
        return self._chain_len

    def close(self) -> None:
        self.recorder.close()


# ===========================================================================
# Reader
# ===========================================================================

_c_torn = DEFAULT.counter(
    "hbbft_obs_flight_torn_tails_total",
    "journal segments whose tail was truncated/corrupt mid-record "
    "(reader skipped the tail loudly)")


def read_segment_bytes(data: bytes,
                       count_torn: bool = True) -> Tuple[List[Any], bool]:
    """Parse one segment's bytes into records.

    Returns ``(records, torn)``: a mid-record truncation, CRC mismatch,
    or undecodable payload ends the segment — ``torn`` is True, the
    damage is counted (``hbbft_obs_flight_torn_tails_total``) and logged,
    and everything before the tear is returned.  Never raises on corrupt
    input.  ``count_torn=False`` skips the counter/log (the recorder's
    lenient startup index pass re-reads segments the audit reader will
    count loudly later — double-counting would fake journal damage).
    """
    records: List[Any] = []
    pos = 0
    n = len(data)
    max_record = _max_record_bytes()
    while pos < n:
        if pos + _FRAME_HEADER.size > n:
            break  # torn: header cut
        length, crc = _FRAME_HEADER.unpack_from(data, pos)
        if length > max_record or pos + 8 + length > n:
            break  # torn: absurd length or payload cut
        payload = data[pos + 8: pos + 8 + length]
        if zlib.crc32(payload) != crc:
            break  # torn: bit rot / partial overwrite
        try:
            # lift the per-blob cap to the record's own CRC-validated
            # length: a legally-journaled near-cap message embeds blobs
            # above MAX_BLOB_BYTES and must not read back as "torn"
            records.append(wire.decode_message(
                payload, max_bytes=max_record, max_blob=len(payload)))
        # hblint: disable=fault-swallowed-drop (accounted two lines down:
        # every break lands in the torn branch that counts
        # hbbft_obs_flight_torn_tails_total and warns)
        except (ValueError, TypeError):
            break  # torn: framing intact but payload undecodable
        pos += 8 + length
    torn = pos < n
    if torn and count_torn:
        _c_torn.inc()
        logger.warning(
            "flight: torn journal tail — %d trailing bytes skipped "
            "after %d records", n - pos, len(records))
    return records, torn


@dataclass
class Journal:
    """One node's parsed journal: records tagged with incarnation."""

    path: str
    node: str
    flavor: str
    records: List[Tuple[int, Any]]   # (incarnation, record)
    torn_tails: int
    incarnations: List[int]

    @property
    def starts(self) -> int:
        return len(self.incarnations)


def read_journal(dirpath: str) -> Journal:
    """Parse every segment of one node's journal directory."""
    segs = []
    for name in sorted(os.listdir(dirpath)):
        m = _SEGMENT_RE.match(name)
        if m:
            segs.append((int(m.group(1)), int(m.group(2)), name))
    segs.sort()
    if not segs:
        raise FileNotFoundError(f"no journal segments in {dirpath!r}")
    records: List[Tuple[int, Any]] = []
    torn = 0
    node = flavor = "?"
    incs: List[int] = []
    for inc, _idx, name in segs:
        with open(os.path.join(dirpath, name), "rb") as fh:
            data = fh.read()
        recs, was_torn = read_segment_bytes(data)
        torn += 1 if was_torn else 0
        if inc not in incs:
            incs.append(inc)
        for rec in recs:
            if isinstance(rec, FlightHello):
                node, flavor = rec.node, rec.flavor
            records.append((inc, rec))
    return Journal(path=dirpath, node=node, flavor=flavor,
                   records=records, torn_tails=torn, incarnations=incs)


def find_journal_dirs(root: str) -> List[str]:
    """``root`` itself if it holds segments, else its segment-holding
    children (the ``examples/cluster.py`` layout: ``root/node-N/``)."""
    def has_segments(d: str) -> bool:
        try:
            return any(_SEGMENT_RE.match(n) for n in os.listdir(d))
        # hblint: disable=fault-swallowed-drop (directory probe: not-a-
        # journal-dir is the expected negative, surfaced by the caller
        # as "no journal segments under …" / audit exit status 2)
        except OSError:
            return False

    if has_segments(root):
        return [root]
    out = []
    try:
        children = sorted(os.listdir(root))
    # hblint: disable=fault-swallowed-drop (same probe: an unreadable
    # root returns empty and the audit entry point exits 2 loudly)
    except OSError:
        return []
    for child in children:
        d = os.path.join(root, child)
        if os.path.isdir(d) and has_segments(d):
            out.append(d)
    return out

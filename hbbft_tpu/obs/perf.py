"""Performance plane: continuous profiling + online headroom model.

The observability stack sees faults (health plane) and latency structure
(causal tracing) but was blind to *capacity*: where CPU goes, how much
slack each layer has, and whether a change silently regressed a hot
path.  :class:`PerfPlane` is the always-on answer, built to cost almost
nothing on the hot path:

- **Sampling by counter snapshot, not by instrumentation.**  The pump
  already attributes its wall time per work segment into the
  ``hbbft_pump_segment_seconds`` histogram and its CPU time per
  iteration into the scheduler's ``cpu_seconds`` accumulator; the span
  tracer already attributes consensus wall time per phase.  The sampler
  reads those cumulative sums once per ``interval_s`` (a dozen float
  reads — no locks, no syscalls beyond two clock reads) and folds the
  *deltas* into bounded ring time-series.  Nothing new runs per message.
- **Clock-free core.**  Every derivation takes ``now`` from the caller;
  the ONE wall-clock read lives in :meth:`PerfPlane.maybe_sample`, the
  sampler entry point (hblint ``determinism`` scope covers this module).
- **Headroom model.**  Per-layer utilization — ``recv`` (ingress
  decode), ``pump`` (protocol state machine), ``crypto`` (threshold
  pairing phases), ``erasure`` (RS/Merkle throughput vs. a calibrated
  reference rate), ``egress`` (coalesced flush) — each a busy-seconds /
  wall-seconds fraction over the window, plus the whole-process CPU
  fraction.  ``headroom = 1 - max(utilization)``: the single scalar the
  bidirectional degradation controller consumes as its slack signal
  (raise batch size only when headroom is real, not inferred).
- **Flame doc + flight journal.**  ``/perf`` serves
  :meth:`PerfPlane.perf_doc` — a flame-style layer→segment tree over the
  retained window plus the raw ring series; every ``snapshot_every``-th
  sample is journaled as a wire-registered ``PerfSnapshot`` flight
  record so the perf history rides the same black box as faults.

Overhead model (documented, bench-gated): one sample per ``interval_s``
touches ~40 Python floats and allocates one small dict; at the default
1 s cadence that is O(10 µs/s) — the ``bench.py --net`` gate holds the
whole plane under 5% of epochs/s against a fresh same-host baseline.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

#: pump segments that are protocol/state-machine work (the pump layer);
#: ``recv`` and ``flush`` are broken out as their own layers and
#: ``queue_wait`` is latency, not busy time (excluded from utilization)
PUMP_SEGMENTS = ("msg", "input", "hello", "startup", "guard", "shed",
                 "deferred")

#: span phases folded into the crypto layer: threshold-decrypt share
#: verification/combination and the common coin are the pairing-heavy
#: phases (span wall time is a proxy for crypto busy time — spans
#: overlap under pipelining, so this can exceed 1.0; it is clamped)
CRYPTO_PHASES = ("decrypt_share", "decrypt_combine", "aba_coin")

#: reference RS/Merkle throughput used to convert erasure bytes/s into a
#: utilization fraction (PR 10/11 measured 300+ MB/s pattern-cached on
#: the build hosts; override per deployment via ``erasure_ref_mbps``)
DEFAULT_ERASURE_REF_MBPS = 300.0

ALL_LAYERS = ("recv", "pump", "crypto", "erasure", "egress")


class PerfPlane:
    """Always-on sampling profiler + headroom model for one node.

    ``registry`` is the node's metric registry (segment/phase histograms
    and byte counters are read from it); ``pump_cpu_fn`` returns the
    scheduler's cumulative pump CPU seconds and ``pump_stats_fn`` its
    ``(iterations, offloaded)`` counters; ``record`` (optional) journals
    a dict snapshot (the runtime wires ``FlightRecorder.record_perf``).
    """

    def __init__(self, registry: Any, node_id: Any, *,
                 interval_s: float = 1.0, ring: int = 240,
                 snapshot_every: int = 10,
                 erasure_ref_mbps: float = DEFAULT_ERASURE_REF_MBPS,
                 pump_cpu_fn: Optional[Callable[[], float]] = None,
                 pump_stats_fn: Optional[
                     Callable[[], Tuple[int, int]]] = None,
                 record: Optional[Callable[..., Any]] = None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.registry = registry
        self.node_id = node_id
        self.interval_s = float(interval_s)
        self.snapshot_every = max(1, int(snapshot_every))
        self.erasure_ref_mbps = float(erasure_ref_mbps)
        self.pump_cpu_fn = pump_cpu_fn
        self.pump_stats_fn = pump_stats_fn
        self.record = record
        #: bounded window ring — the whole retained perf history
        self.windows: Deque[Dict[str, Any]] = deque(maxlen=int(ring))
        self.samples = 0
        self._last_t: Optional[float] = None
        self._prev: Optional[Dict[str, float]] = None
        # the model's own exposition: latest headroom / per-layer
        # utilization as gauges (scrapeable without /perf) and a sample
        # counter so an operator can tell a stalled sampler from an
        # idle node
        self._g_headroom = registry.gauge(
            "hbbft_perf_headroom",
            "latest measured headroom (1 = idle, 0 = saturated; -1 "
            "until the sampler's first complete window)")
        self._g_util = registry.gauge(
            "hbbft_perf_util",
            "latest per-layer utilization fraction over the sampling "
            "window (recv/pump/crypto/erasure/egress busy seconds per "
            "wall second; cpu = whole-process CPU fraction)",
            labelnames=("layer",), max_label_sets=len(ALL_LAYERS) + 2)
        self._c_samples = registry.counter(
            "hbbft_perf_samples_total",
            "completed perf-plane sampling windows")
        self._g_headroom.set(-1)
        for layer in ALL_LAYERS + ("cpu",):
            self._g_util.labels(layer=layer)

    # -- the one wall-clock entry point ---------------------------------------

    def maybe_sample(self, now: Optional[float] = None) -> Optional[dict]:
        """Rate-limited sampler: called from the pump tick (so it never
        races an iteration); samples at most once per ``interval_s``.
        The only wall-clock read in the module lives here — everything
        below takes ``now`` from its caller."""
        if now is None:
            # hblint: disable=det-wall-clock (the sampler entry point:
            # the perf plane measures REAL elapsed time by contract;
            # sim/test callers pass their own `now`)
            now = time.monotonic()
        if self._last_t is not None and now - self._last_t < self.interval_s:
            return None
        return self.sample(now)

    # -- clock-free core ------------------------------------------------------

    def _snapshot_counters(self) -> Dict[str, float]:
        """One flat read of every cumulative source the model consumes."""
        snap: Dict[str, float] = {}
        seg_h = self.registry.get("hbbft_pump_segment_seconds")
        if seg_h is not None:
            for seg in PUMP_SEGMENTS + ("recv", "flush"):
                child = seg_h.labels(segment=seg)
                snap[f"seg:{seg}:sum"] = child.sum
                snap[f"seg:{seg}:count"] = float(child.count)
        ph_h = self.registry.get("hbbft_phase_duration_seconds")
        if ph_h is not None:
            for ph in CRYPTO_PHASES:
                child = ph_h.labels(phase=ph)
                snap[f"phase:{ph}:sum"] = child.sum
        ers = self.registry.get("hbbft_rbc_erasure_bytes_total")
        snap["erasure_bytes"] = ers.total() if ers is not None else 0.0
        sent = self.registry.get("hbbft_net_bytes_sent_total")
        snap["sent_bytes"] = sent.value() if sent is not None else 0.0
        snap["proc_cpu"] = time.process_time()
        if self.pump_cpu_fn is not None:
            snap["pump_cpu"] = float(self.pump_cpu_fn())
        if self.pump_stats_fn is not None:
            it, off = self.pump_stats_fn()
            snap["pump_iters"] = float(it)
            snap["pump_offloaded"] = float(off)
        return snap

    def sample(self, now: float) -> Optional[dict]:
        """Fold one window: deltas of every cumulative source since the
        previous sample → per-segment busy fractions, per-layer
        utilization, and the headroom scalar.  Returns the window dict
        (also appended to the bounded ring), or None on the priming
        sample (no previous snapshot to delta against)."""
        snap = self._snapshot_counters()
        prev, self._prev = self._prev, snap
        last_t, self._last_t = self._last_t, now
        if prev is None or last_t is None:
            return None
        dt = now - last_t
        if dt <= 0:
            return None

        def delta(key: str) -> float:
            return max(0.0, snap.get(key, 0.0) - prev.get(key, 0.0))

        segments: Dict[str, Dict[str, float]] = {}
        for seg in PUMP_SEGMENTS + ("recv", "flush"):
            busy = delta(f"seg:{seg}:sum")
            events = delta(f"seg:{seg}:count")
            if events <= 0 and busy <= 0:
                continue
            segments[seg] = {
                "busy_s": busy,
                "events": int(events),
                "mean_s": (busy / events) if events > 0 else 0.0,
                "frac": min(1.0, busy / dt),
            }

        def seg_busy(names) -> float:
            return sum(segments.get(s, {}).get("busy_s", 0.0)
                       for s in names)

        crypto_busy = sum(delta(f"phase:{p}:sum") for p in CRYPTO_PHASES)
        erasure_bps = delta("erasure_bytes") / dt
        layers = {
            "recv": min(1.0, seg_busy(("recv",)) / dt),
            "pump": min(1.0, seg_busy(PUMP_SEGMENTS) / dt),
            "crypto": min(1.0, crypto_busy / dt),
            "erasure": min(1.0, erasure_bps
                           / (self.erasure_ref_mbps * 1e6)),
            "egress": min(1.0, seg_busy(("flush",)) / dt),
        }
        cpu_frac = min(1.0, delta("proc_cpu") / dt)
        pump_cpu_frac = (min(1.0, delta("pump_cpu") / dt)
                         if "pump_cpu" in snap else None)
        util = max(max(layers.values()), cpu_frac)
        window = {
            "t": now,
            "wall_s": dt,
            "cpu_frac": cpu_frac,
            "pump_cpu_frac": pump_cpu_frac,
            "layers": layers,
            "segments": segments,
            "headroom": max(0.0, 1.0 - util),
        }
        if "pump_iters" in snap:
            iters = delta("pump_iters")
            window["pump_iters"] = int(iters)
            window["offload_frac"] = (
                delta("pump_offloaded") / iters if iters > 0 else 0.0)
        self.windows.append(window)
        self.samples += 1
        self._c_samples.inc()
        self._g_headroom.set(window["headroom"])
        for layer, frac in layers.items():
            self._g_util.labels(layer=layer).set(frac)
        self._g_util.labels(layer="cpu").set(cpu_frac)
        if self.record is not None and (
                self.samples % self.snapshot_every == 0):
            self.record(window_s=dt, cpu_frac=cpu_frac,
                        headroom=window["headroom"],
                        doc=json.dumps({"layers": layers,
                                        "segments": segments},
                                       sort_keys=True))
        return window

    # -- derived views --------------------------------------------------------

    def headroom(self) -> Optional[float]:
        """Latest headroom scalar (1 = idle, 0 = saturated), or None
        before the first complete window — callers (the controller's
        slack input) must treat None as "no evidence of slack"."""
        if not self.windows:
            return None
        return self.windows[-1]["headroom"]

    def utilization(self) -> Dict[str, float]:
        """Latest per-layer utilization ({} before the first window)."""
        if not self.windows:
            return {}
        return dict(self.windows[-1]["layers"])

    def summary(self) -> Dict[str, Any]:
        """Compact dict for ``/status`` (`status_doc()['perf']`)."""
        if not self.windows:
            return {"samples": self.samples, "headroom": None, "util": {}}
        w = self.windows[-1]
        return {
            "samples": self.samples,
            "headroom": w["headroom"],
            "util": {k: round(v, 4) for k, v in w["layers"].items()},
            "cpu_frac": round(w["cpu_frac"], 4),
        }

    def perf_doc(self) -> Dict[str, Any]:
        """The ``/perf`` document: a flame-style layer→segment tree of
        busy seconds aggregated over the retained ring, plus the raw
        window series (newest last) for time-axis consumers."""
        agg_seg: Dict[str, float] = {}
        agg_layer: Dict[str, float] = {k: 0.0 for k in ALL_LAYERS}
        wall = 0.0
        for w in self.windows:
            wall += w["wall_s"]
            for seg, s in w["segments"].items():
                agg_seg[seg] = agg_seg.get(seg, 0.0) + s["busy_s"]
            for layer, frac in w["layers"].items():
                agg_layer[layer] += frac * w["wall_s"]

        def seg_children(names) -> List[dict]:
            return [{"name": s, "value": round(agg_seg[s], 6)}
                    for s in names if agg_seg.get(s, 0.0) > 0.0]

        layer_segs = {"recv": ("recv",), "pump": PUMP_SEGMENTS,
                      "egress": ("flush",)}
        flame = {
            "name": f"node{self.node_id}",
            "value": round(wall, 6),
            "children": [
                {"name": layer,
                 "value": round(agg_layer[layer], 6),
                 "children": seg_children(layer_segs.get(layer, ()))}
                for layer in ALL_LAYERS
            ],
        }
        return {
            "node": self.node_id,
            "interval_s": self.interval_s,
            "samples": self.samples,
            "windows": len(self.windows),
            "headroom": self.headroom(),
            "util": self.utilization(),
            "flame": flame,
            "series": list(self.windows),
        }


def segment_means(metrics: Dict[str, Any],
                  prev: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Dict[str, float]]:
    """Per-segment ``{mean_s, busy_s, events}`` from a parsed
    ``/metrics`` exposition (``parse_prometheus_text`` output) —
    optionally as a delta against an earlier scrape of the same node.
    This is the shared read path of the watchtower's perf-drift sentinel
    and ``bench.py``'s pump-utilization lines / frozen profiles."""

    def fold(parsed, suffix):
        out: Dict[str, float] = {}
        for labels, v in parsed.get(
                f"hbbft_pump_segment_seconds_{suffix}", []):
            seg = labels.get("segment")
            if seg is not None:
                out[seg] = out.get(seg, 0.0) + v
        return out

    sums, counts = fold(metrics, "sum"), fold(metrics, "count")
    if prev is not None:
        psums, pcounts = fold(prev, "sum"), fold(prev, "count")
        sums = {s: v - psums.get(s, 0.0) for s, v in sums.items()}
        counts = {s: v - pcounts.get(s, 0.0) for s, v in counts.items()}
    out: Dict[str, Dict[str, float]] = {}
    for seg, n in counts.items():
        if n <= 0:
            continue
        busy = max(0.0, sums.get(seg, 0.0))
        out[seg] = {"mean_s": busy / n, "busy_s": busy, "events": n}
    return out

"""Incremental audit core: streaming forensics over live flight journals.

The post-mortem auditor (:mod:`hbbft_tpu.obs.audit`) historically read
every journal in full, then verified invariants in one batch pass.  This
module is the refactored **incremental core** the batch CLI is rebuilt
on: an :class:`IncrementalAuditor` accumulates exactly the state the
batch pass built — outbound payload index, equivocation slots, commit
chains, overload attribution, VID corroboration — one record at a time,
and :meth:`IncrementalAuditor.result` derives a full
:class:`~hbbft_tpu.obs.audit.AuditResult` from that state at any moment.
Feeding a completed journal set record-for-record yields a verdict
**byte-identical** to the old batch pass (regression-tested in
``tests/test_obs_audit.py``), while a live consumer (the watchtower,
:mod:`hbbft_tpu.obs.watch`) can call ``result()`` every poll tick and
see a fork or a conflicting (sender, slot) value seconds after the
evidence lands in a journal segment.

:class:`JournalTailer` is the disk side of streaming: it re-discovers
journal directories each poll, remembers a byte offset per segment file,
and parses only the appended suffix with the same framing validation as
:func:`hbbft_tpu.obs.flight.read_segment_bytes` — a partial frame at the
tail of the *active* (newest) segment is simply retried next poll, and
only becomes a counted torn tail once the segment has rotated (or on
:meth:`JournalTailer.finalize`), mirroring the batch reader's
crash-tolerance.

State bounds: verdict-bearing state grows with the protocol (commit
chain length, distinct equivocation slots, offending peers), not with
wall-clock message volume — except the display timeline and the
send/receive matching index, which a live consumer caps via
``max_events`` (overflow is counted in ``events_dropped``, never
silent).
"""

from __future__ import annotations

import hashlib
import os
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.fault_log import FaultKind, equivocation_kinds
from hbbft_tpu.obs.flight import (
    _FRAME_HEADER,
    _SEGMENT_RE,
    _max_record_bytes,
    FlightCommit,
    FlightFault,
    FlightHello,
    FlightMsg,
    FlightNote,
    FlightSpan,
    find_journal_dirs,
    target_covers,
)
from hbbft_tpu.obs.metrics import DEFAULT
from hbbft_tpu.protocols import wire

#: timeline ordering rank per record family (notes lead their epoch,
#: then sends/receives, commits close it, spans/faults trail as derived)
_RANK = {"note": 0, "msg": 1, "commit": 2, "span": 3, "fault": 4}


#: FlightFault kinds that are protocol-layer overload evidence (flood
#: budgets engaging), as opposed to protocol misbehavior of other shapes
_OVERLOAD_FAULT_KINDS = frozenset({
    "FutureEpochFlood", "SubsetMessageFlood",
})


def _parse_guard_note(detail: str) -> Optional[Dict[str, str]]:
    """``kind=K peer=P …`` → {kind, peer[, claimed]} (the runtime's
    overload-guard journal format; see NodeRuntime._process_guard_event).
    ``auth_fail`` notes carry both sides of a spoof: ``peer`` is the
    ATTACKER's socket endpoint, ``claimed`` the impersonated identity —
    keeping them separate is what lets the incident report blame the
    endpoint without smearing the victim."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    if "kind" not in fields or "peer" not in fields:
        return None
    out = {"kind": fields["kind"], "peer": fields["peer"]}
    if "claimed" in fields:
        out["claimed"] = fields["claimed"]
    return out


def _parse_statesync_note(detail: str) -> Optional[Dict[str, Any]]:
    """``index=N head=HEX`` → {index, head} (the boundary a snapshot
    joiner's runtime journals at activation)."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    try:
        return {"index": int(fields["index"]), "head": fields["head"]}
    # hblint: disable=fault-swallowed-drop (accounted at the caller: a
    # None return lands in sync_mismatches and flips the verdict to fork)
    except (KeyError, ValueError):
        return None


def _parse_vid_note(detail: str) -> Optional[Dict[str, str]]:
    """``root=HEX … payload_sha3=D`` → field dict (the runtime's VID
    journal format: ``vid_cert`` notes from the proposer anchor the
    payload digest behind a dispersed root; ``vid_retrieved`` notes from
    every resolver must corroborate it)."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    if "root" not in fields or "payload_sha3" not in fields:
        return None
    return fields


def _digest(payload: bytes) -> str:
    return hashlib.sha3_256(payload).hexdigest()[:16]


# ===========================================================================
# Equivocation slots
# ===========================================================================


def equivocation_key(msg: Any
                     ) -> Optional[Tuple[Tuple, bytes, FaultKind]]:
    """``(slot, value, FaultKind)`` for messages where one sender emitting
    two *different* values for the same slot is proof of equivocation;
    ``None`` for messages that may legitimately repeat with different
    values (BVal/Aux vote for both sides honestly, EpochStarted
    re-announces).  The slot includes everything that scopes the value;
    the sender is supplied by the caller."""
    from hbbft_tpu.protocols.binary_agreement import (
        CoinMsg, ConfMsg, TermMsg,
    )
    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
    )
    from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap
    from hbbft_tpu.protocols.honey_badger import (
        DecryptionShareWrap, SubsetWrap,
    )
    from hbbft_tpu.protocols.sender_queue import AlgoMessage
    from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap

    era = 0
    if isinstance(msg, AlgoMessage):
        msg = msg.msg
    if isinstance(msg, HbWrap):
        era = msg.era
        msg = msg.msg
    if isinstance(msg, DecryptionShareWrap):
        share = msg.msg.share
        return ((era, msg.epoch, "decrypt", repr(msg.proposer_id)),
                share.to_bytes(), FaultKind.MultipleDecryptionShares)
    if not isinstance(msg, SubsetWrap):
        return None
    epoch = msg.epoch
    inner = msg.msg
    if isinstance(inner, BroadcastWrap):
        proposer = repr(inner.proposer_id)
        m = inner.msg
        rules = (
            (ValueMsg, "value", FaultKind.MultipleValues),
            (EchoMsg, "echo", FaultKind.MultipleEchos),
            (EchoHashMsg, "echo_hash", FaultKind.MultipleEchoHashes),
            (CanDecodeMsg, "can_decode", FaultKind.MultipleCanDecodes),
            (ReadyMsg, "ready", FaultKind.MultipleReadys),
        )
        for cls, tag, kind in rules:
            if isinstance(m, cls):
                root = m.proof.root_hash if isinstance(
                    m, (ValueMsg, EchoMsg)) else m.root
                return ((era, epoch, "rbc", proposer, tag), root, kind)
        return None
    if isinstance(inner, AgreementWrap):
        proposer = repr(inner.proposer_id)
        m = inner.msg
        if isinstance(m, ConfMsg):
            value = bytes([(False in m.values)
                           | ((True in m.values) << 1)])
            return ((era, epoch, "aba", proposer, "conf", m.epoch),
                    value, FaultKind.MultipleConf)
        if isinstance(m, TermMsg):
            return ((era, epoch, "aba", proposer, "term"),
                    b"\x01" if m.value else b"\x00",
                    FaultKind.MultipleTerm)
        if isinstance(m, CoinMsg):
            inner_msg = m.msg
            share = getattr(inner_msg, "share", None)
            if share is not None:
                return ((era, epoch, "aba", proposer, "coin", m.epoch),
                        share.to_bytes(),
                        FaultKind.MultipleSignatureShares)
    return None


# ===========================================================================
# Result model
# ===========================================================================


@dataclass
class Event:
    """One timeline entry (sort-stable canonical key + display line)."""

    era: int
    epoch: int
    rank: int
    key: Tuple
    line: str


@dataclass
class AuditResult:
    nodes: List[str] = field(default_factory=list)
    events: List[Event] = field(default_factory=list)
    chains: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    first_divergence: Optional[Dict[str, Any]] = None
    self_conflicts: List[Dict[str, Any]] = field(default_factory=list)
    monotonicity_violations: List[Dict[str, Any]] = field(
        default_factory=list)
    equivocations: List[Dict[str, Any]] = field(default_factory=list)
    unmatched_receives: int = 0
    decode_failures: int = 0
    torn_tails: int = 0
    restarts: Dict[str, int] = field(default_factory=dict)
    status_mismatches: List[str] = field(default_factory=list)
    # membership lifecycle: nodes that activated from a state-sync
    # snapshot (the journal's ``statesync`` note declares the claimed
    # chain boundary), with the boundary verified against every other
    # journal's digest at the preceding index
    sync_joins: List[Dict[str, Any]] = field(default_factory=list)
    sync_mismatches: List[str] = field(default_factory=list)
    # conflicting slot values that attribute cleanly to DIFFERENT
    # incarnations of the sender (its own journal shows each value sent
    # exactly once, by a different process life): the expected amnesia
    # artifact of a crash-restart without persistence re-proposing into
    # already-decided epochs — reported, but not a fault verdict.  True
    # equivocation (two values inside one incarnation, or a value the
    # sender never journaled sending — the tampering shape) still is.
    restart_reproposals: List[Dict[str, Any]] = field(
        default_factory=list)
    # VID cert-vs-retrieval corroboration: every ``vid_retrieved`` note's
    # payload digest must agree with the proposer's ``vid_cert`` anchor
    # and with every other resolver of the same root.  Two digests behind
    # one committed root is a content fork — the ordered commitment was
    # unambiguous but nodes read different payloads through it.
    # Uncorroborated roots (proposer journal rotated, no retrieval yet)
    # are benign and merely counted.
    vid_roots: int = 0
    vid_corroborated: int = 0
    vid_inconsistencies: List[Dict[str, Any]] = field(
        default_factory=list)
    # resource-exhaustion forensics: journaled ``guard`` notes (ingress
    # throttle escalations, SenderQueue backlog evictions, hello rejects
    # — written by the runtime's overload defense) plus protocol-layer
    # flood faults (FutureEpochFlood / SubsetMessageFlood), aggregated
    # per OFFENDING peer so an incident attributes to the spamming node.
    # Defense working as designed is not a fault verdict.
    overload_incidents: List[Dict[str, Any]] = field(default_factory=list)
    # timeline entries a bounded live consumer dropped past its
    # ``max_events`` cap (always 0 in the unbounded batch audit)
    events_dropped: int = 0

    @property
    def first_affected_epoch(self) -> Optional[Tuple[int, int]]:
        keys = [(e["era"], e["epoch"]) for e in self.equivocations]
        return min(keys) if keys else None

    @property
    def verdict(self) -> str:
        if self.first_divergence or self.self_conflicts \
                or self.status_mismatches or self.sync_mismatches \
                or self.vid_inconsistencies:
            return "fork"
        if self.equivocations or self.monotonicity_violations:
            return "fault"
        return "clean"

    def as_dict(self) -> Dict[str, Any]:
        fa = self.first_affected_epoch
        return {
            "verdict": self.verdict,
            "nodes": self.nodes,
            "restarts": self.restarts,
            "torn_tails": self.torn_tails,
            "decode_failures": self.decode_failures,
            "unmatched_receives": self.unmatched_receives,
            "chains": {
                n: {"head": c["head"], "len": c["len"]}
                for n, c in self.chains.items()
            },
            "first_divergence": self.first_divergence,
            "self_conflicts": self.self_conflicts,
            "monotonicity_violations": self.monotonicity_violations,
            "equivocations": self.equivocations,
            "first_affected_epoch": list(fa) if fa else None,
            "status_mismatches": self.status_mismatches,
            "sync_joins": self.sync_joins,
            "sync_mismatches": self.sync_mismatches,
            "restart_reproposals": self.restart_reproposals,
            "overload_incidents": self.overload_incidents,
            "vid_roots": self.vid_roots,
            "vid_corroborated": self.vid_corroborated,
            "vid_inconsistencies": self.vid_inconsistencies,
        }


def _is_restart_reproposal(vals: Dict[str, Any],
                           sent: Optional[Dict[str, set]]) -> bool:
    """Do the conflicting values attribute cleanly to different process
    incarnations of the sender?  Requires the sender's own journal to
    show EVERY witnessed value being sent, each by exactly one
    incarnation, all incarnations distinct — the amnesia shape of a
    crash-restart re-proposing into already-decided epochs.  Anything
    less (a value the sender never journaled — tampering; two values in
    one incarnation — equivocation; rotated-away sender evidence) stays
    slashing-grade."""
    if sent is None:
        return False
    if set(vals) - set(sent):
        return False
    incs = [sent[d] for d in vals]
    if any(len(s) != 1 for s in incs):
        return False
    flat = [next(iter(s)) for s in incs]
    return len(set(flat)) == len(flat)


# ===========================================================================
# Incremental core
# ===========================================================================


class IncrementalAuditor:
    """Record-at-a-time accumulation of the audit state.

    ``feed(node, incarnation, record)`` applies one journal record;
    ``result()`` derives a complete :class:`AuditResult` from whatever
    has been fed so far and may be called repeatedly (every watchtower
    poll tick).  The derivation re-runs only the cross-record sections
    (timeline sort, overload attribution order, VID corroboration,
    digest-chain divergence scan, sync-join verification, equivocation
    vs restart-re-proposal classification) — all accumulation is
    single-pass at feed time.

    Send/receive matching is deferred to ``result()`` because a tailer
    may surface a receive before the matching send's journal bytes: the
    batch pass indexed every outbound payload before walking any
    receive, and deferring the check reproduces that order-independence
    exactly.

    ``max_events`` bounds the display timeline (the only state that
    grows per message rather than per protocol object a live consumer
    cares about); overflow is counted in ``events_dropped``.
    """

    def __init__(self, max_events: Optional[int] = None):
        self.max_events = max_events
        self._nodes: List[str] = []            # first-seen journal order
        self._incs: Dict[str, List[int]] = {}  # node → incarnations seen
        self.torn_tails = 0
        self.decode_failures = 0
        self.events_dropped = 0
        self._events: List[Event] = []
        # sender node → payload digest → [(incarnation, FlightMsg)]
        self._out_index: Dict[
            str, Dict[str, List[Tuple[int, FlightMsg]]]] = {}
        # deferred receive matching: (sender, digest, receiver) → count
        self._recv_pending: Dict[Tuple[str, str, str], int] = {}
        # slots[(sender, slot, kind)] = {value_digest: set(witnesses)}
        self._slots: Dict[Tuple, Dict[str, set]] = {}
        # the sender's own account: per slot, which incarnation(s)
        # journaled SENDING each value — what separates a crash-restart
        # re-proposal from equivocation/tampering
        self._slot_sends: Dict[Tuple, Dict[str, set]] = {}
        self._commits: Dict[str, Dict[int, Tuple[str, int, int, int]]] = {}
        self._last_key: Dict[Tuple[str, int], Tuple[int, int]] = {}
        # overload[peer] = {"kinds": {...}, "witnesses": set, "claimed": set}
        self._overload: Dict[str, Dict[str, Any]] = {}
        # vid[root] = {payload_sha3: {"cert:<node>" | "retr:<node>", ...}}
        self._vid: Dict[str, Dict[str, set]] = {}
        self._vid_anchored: set = set()
        # feed-time findings, copied into each result()
        self._self_conflicts: List[Dict[str, Any]] = []
        self._monotonicity: List[Dict[str, Any]] = []
        self._sync_joins: List[Dict[str, Any]] = []
        self._sync_malformed: List[str] = []
        self._vid_malformed: List[Dict[str, Any]] = []
        self.records_fed = 0

    # -- registration --------------------------------------------------------

    def add_node(self, node: str) -> None:
        """Declare a journal's node (first-seen order fixes the report's
        node order, matching the batch pass's journal order)."""
        if node not in self._incs:
            self._incs[node] = []
            self._nodes.append(node)

    def observe_incarnation(self, node: str, inc: int) -> None:
        self.add_node(node)
        incs = self._incs[node]
        if inc not in incs:
            incs.append(inc)

    def add_torn(self, n: int = 1) -> None:
        self.torn_tails += n

    def _event(self, ev: Event) -> None:
        if self.max_events is not None \
                and len(self._events) >= self.max_events:
            self.events_dropped += 1
            return
        self._events.append(ev)

    # -- per-record accumulation ---------------------------------------------

    def feed(self, node: str, inc: int, rec: Any) -> None:
        """Apply one journal record (tagged with the process incarnation
        that wrote it) to the audit state."""
        self.observe_incarnation(node, inc)
        self.records_fed += 1
        if isinstance(rec, FlightMsg):
            self._feed_msg(node, inc, rec)
        elif isinstance(rec, FlightCommit):
            self._feed_commit(node, inc, rec)
        elif isinstance(rec, FlightFault):
            self._event(Event(
                rec.era, rec.epoch, _RANK["fault"],
                ("fault", rec.kind, rec.node, node, inc, rec.seq),
                f"era={rec.era} ep={rec.epoch} fault {rec.kind} "
                f"by {rec.node} seen@{node}#{inc}"))
            if rec.kind in _OVERLOAD_FAULT_KINDS:
                self._overload_hit(rec.node, rec.kind, node)
        elif isinstance(rec, FlightSpan):
            rnd = "-" if rec.round is None else rec.round
            self._event(Event(
                rec.era, rec.epoch, _RANK["span"],
                ("span", rec.name, rnd, node, inc, rec.seq),
                f"era={rec.era} ep={rec.epoch} span {rec.name} "
                f"r={rnd} n={rec.count} @{node}#{inc}"))
        elif isinstance(rec, FlightNote):
            self._feed_note(node, inc, rec)
        # FlightHello / FlightTrace carry no audit invariants

    def _feed_msg(self, node: str, inc: int, rec: FlightMsg) -> None:
        d = _digest(rec.payload) if rec.payload else "-"
        if rec.direction == "in":
            line = (f"era={rec.era} ep={rec.epoch} msg "
                    f"{rec.mtype} {d} {rec.peer}->{node} "
                    f"in@{node}#{inc}.{rec.seq}")
        else:
            line = (f"era={rec.era} ep={rec.epoch} msg "
                    f"{rec.mtype} {d} {node}->({rec.peer}) "
                    f"out@{node}#{inc}.{rec.seq}")
        self._event(Event(
            rec.era, rec.epoch, _RANK["msg"],
            (rec.mtype, d, 0 if rec.direction == "out" else 1,
             node, inc, rec.seq), line))
        if rec.direction == "out" and rec.payload:
            self._out_index.setdefault(node, {}).setdefault(
                d, []).append((inc, rec))
            # the sender's own account of what it emitted for each
            # equivocation slot, tagged with the process incarnation
            # that sent it
            try:
                msg = wire.decode_message(rec.payload)
            except (ValueError, TypeError):
                self.decode_failures += 1
                return
            eq = equivocation_key(msg)
            if eq is not None:
                slot, value, kind = eq
                self._slot_sends.setdefault(
                    (node, slot, kind), {}).setdefault(
                    _digest(value), set()).add(inc)
        if rec.direction != "in" or not rec.payload:
            return
        # receive↔send matching is resolved at result() time, once the
        # sender's outbound index is as complete as it is going to get
        key = (rec.peer, d, node)
        self._recv_pending[key] = self._recv_pending.get(key, 0) + 1
        # equivocation slots are receiver-side evidence
        try:
            msg = wire.decode_message(rec.payload)
        except (ValueError, TypeError):
            self.decode_failures += 1
            return
        eq = equivocation_key(msg)
        if eq is not None:
            slot, value, kind = eq
            vals = self._slots.setdefault((rec.peer, slot, kind), {})
            vals.setdefault(_digest(value), set()).add(node)

    def _feed_commit(self, node: str, inc: int, rec: FlightCommit) -> None:
        per_index = self._commits.setdefault(node, {})
        dig = rec.digest.hex()
        self._event(Event(
            rec.era, rec.epoch, _RANK["commit"],
            ("commit", rec.index, node, inc, rec.seq),
            f"era={rec.era} ep={rec.epoch} commit "
            f"idx={rec.index} {dig[:16]} @{node}#{inc}"))
        prev = per_index.get(rec.index)
        if prev is not None and prev[0] != dig:
            self._self_conflicts.append({
                "node": node, "index": rec.index,
                "digests": sorted((prev[0][:16], dig[:16])),
            })
        else:
            per_index[rec.index] = (dig, rec.era, rec.epoch, inc)
        last = self._last_key.get((node, inc))
        if last is not None and (rec.era, rec.epoch) <= last:
            self._monotonicity.append({
                "node": node, "incarnation": inc,
                "prev": list(last),
                "next": [rec.era, rec.epoch],
            })
        self._last_key[(node, inc)] = (rec.era, rec.epoch)

    def _feed_note(self, node: str, inc: int, rec: FlightNote) -> None:
        self._event(Event(
            0, 0, _RANK["note"],
            ("note", rec.kind, node, inc, rec.seq),
            f"note {rec.kind} {rec.detail} @{node}#{inc}"))
        if rec.kind == "statesync":
            join = _parse_statesync_note(rec.detail)
            if join is None:
                self._sync_malformed.append(
                    f"{node}#{inc}: malformed statesync note "
                    f"{rec.detail!r}")
            else:
                join.update({"node": node, "incarnation": inc})
                self._sync_joins.append(join)
        elif rec.kind == "guard":
            hit = _parse_guard_note(rec.detail)
            if hit is not None:
                self._overload_hit(hit["peer"], hit["kind"], node,
                                   hit.get("claimed"))
        elif rec.kind in ("vid_cert", "vid_retrieved"):
            fields = _parse_vid_note(rec.detail)
            if fields is None:
                self._vid_malformed.append({
                    "root": "?",
                    "error": f"malformed {rec.kind} note "
                             f"{rec.detail!r} @{node}#{inc}",
                })
                return
            sha3 = fields["payload_sha3"]
            if sha3 == "none":
                # failed retrieval — already surfaced through the
                # vid_mismatch/vid_exhausted notes and the proposer
                # fault; no digest to corroborate
                return
            tag = "cert" if rec.kind == "vid_cert" else "retr"
            self._vid.setdefault(fields["root"], {}).setdefault(
                sha3, set()).add(f"{tag}:{node}")
            if rec.kind == "vid_cert":
                self._vid_anchored.add(fields["root"])

    def _overload_hit(self, peer: str, kind: str, witness: str,
                      claimed: Optional[str] = None) -> None:
        entry = self._overload.setdefault(
            peer, {"kinds": {}, "witnesses": set(), "claimed": set()})
        entry["kinds"][kind] = entry["kinds"].get(kind, 0) + 1
        entry["witnesses"].add(witness)
        if claimed is not None:
            entry["claimed"].add(claimed)

    # -- derivation ----------------------------------------------------------

    def result(self) -> AuditResult:
        """Derive a full :class:`AuditResult` from the state fed so far.

        Safe to call repeatedly; the accumulated state is never mutated
        by the derivation (sync-join entries are copied before the
        boundary verdict is stamped on them)."""
        res = AuditResult()
        res.nodes = list(self._nodes)
        res.restarts = {n: max(0, len(self._incs[n]) - 1)
                        for n in self._nodes}
        res.torn_tails = self.torn_tails
        res.decode_failures = self.decode_failures
        res.events_dropped = self.events_dropped
        res.events = sorted(
            self._events, key=lambda e: (e.era, e.epoch, e.rank, e.key))
        res.self_conflicts = list(self._self_conflicts)
        res.monotonicity_violations = list(self._monotonicity)
        res.sync_joins = [dict(j) for j in self._sync_joins]
        res.sync_mismatches = list(self._sync_malformed)
        res.vid_inconsistencies = list(self._vid_malformed)

        # deferred send↔receive matching against the now-complete index
        for (sender, d, node), count in self._recv_pending.items():
            if sender not in self._incs:
                continue  # no journal for the sender — nothing to match
            outs = self._out_index.get(sender, {}).get(d, ())
            if not any(target_covers(o.peer, node) for _i, o in outs):
                res.unmatched_receives += count

        # resource-exhaustion attribution: most-implicated peer first
        res.overload_incidents = [
            {
                "peer": peer,
                "kinds": dict(sorted(entry["kinds"].items())),
                "witnesses": sorted(entry["witnesses"]),
                "events": sum(entry["kinds"].values()),
                # spoof attribution: the identities this endpoint
                # CLAIMED while failing authentication (distinct from
                # "peer" — the impersonated validator is the victim,
                # not the attacker)
                **({"claimed_identities": sorted(entry["claimed"])}
                   if entry["claimed"] else {}),
            }
            for peer, entry in sorted(
                self._overload.items(),
                key=lambda kv: (-sum(kv[1]["kinds"].values()), kv[0]),
            )
        ]

        # -- VID cert-vs-retrieval consistency -------------------------------
        # One root, one payload: the proposer's vid_cert digest and
        # every resolver's vid_retrieved digest must be THE same sha3.
        # A root only counts as corroborated when at least two
        # independent accounts agree (cert + a retrieval, or two
        # retrievals); a lone account is benign but proves nothing.
        res.vid_roots = len(self._vid)
        for root in sorted(self._vid):
            digests = self._vid[root]
            if len(digests) > 1:
                res.vid_inconsistencies.append({
                    "root": root,
                    "anchored": root in self._vid_anchored,
                    "digests": {d: sorted(w)
                                for d, w in sorted(digests.items())},
                })
            elif sum(len(w) for w in digests.values()) >= 2:
                res.vid_corroborated += 1

        # -- digest-chain agreement ------------------------------------------
        for node, per_index in self._commits.items():
            if per_index:
                top = max(per_index)
                res.chains[node] = {
                    "len": top + 1,
                    "head": per_index[top][0],
                    "commits": per_index,
                }
        all_indices = sorted(
            {i for c in self._commits.values() for i in c})
        for i in all_indices:
            present = {n: c[i]
                       for n, c in self._commits.items() if i in c}
            if len({v[0] for v in present.values()}) > 1:
                res.first_divergence = {
                    "index": i,
                    "per_node": {
                        n: {"digest": v[0][:16], "era": v[1],
                            "epoch": v[2]}
                        for n, v in sorted(present.items())
                    },
                    "era": min(v[1] for v in present.values()),
                    "epoch": min(v[2] for v in present.values()),
                }
                break

        # -- membership-lifecycle boundaries ---------------------------------
        # A state-sync join claims "my chain starts at index k with
        # head H".  That claim must match what the rest of the cluster
        # committed: any journal holding index k−1 must hold digest H
        # there.  A joiner whose claimed boundary nobody can
        # corroborate stays unverified (benign: donors' journals may
        # have rotated past it); a CONTRADICTED boundary is a fork.
        for join in res.sync_joins:
            idx, head = join["index"], join["head"]
            verified = None
            for other, per_index in self._commits.items():
                prev = per_index.get(idx - 1)
                if prev is None:
                    continue
                if prev[0] == head:
                    verified = other
                else:
                    res.sync_mismatches.append(
                        f"{join['node']} joined claiming "
                        f"chain[{idx - 1}] = {head[:16]} but {other} "
                        f"committed {prev[0][:16]} there")
                    verified = None
                    break
            join["verified_against"] = verified

        # -- equivocation evidence -------------------------------------------
        eq_kinds = equivocation_kinds()
        for (sender, slot, kind), vals in sorted(
                self._slots.items(), key=lambda kv: repr(kv[0])):
            if len(vals) < 2:
                continue
            assert kind in eq_kinds
            entry = {
                "sender": sender,
                "kind": kind.name,
                "era": slot[0],
                "epoch": slot[1],
                "slot": repr(slot),
                "values": {d: sorted(w)
                           for d, w in sorted(vals.items())},
            }
            if _is_restart_reproposal(vals, self._slot_sends.get(
                    (sender, slot, kind))):
                res.restart_reproposals.append(entry)
            else:
                res.equivocations.append(entry)
        return res


# ===========================================================================
# Journal tailing
# ===========================================================================

_c_stream_torn = DEFAULT.counter(
    "hbbft_obs_stream_torn_tails_total",
    "rotated/finalized journal segments the streaming auditor found "
    "torn mid-record (skipped loudly, like the batch reader)")
_c_stream_records = DEFAULT.counter(
    "hbbft_obs_stream_records_total",
    "journal records consumed by the streaming auditor's tailer")
_c_stream_read_fail = DEFAULT.counter(
    "hbbft_obs_stream_read_failures_total",
    "journal segment reads the tailer could not complete (I/O error); "
    "retried on the next poll")


@dataclass
class _SegmentCursor:
    """Per-segment tail state: how many bytes have been consumed, and
    whether the segment is finished (fully parsed or counted torn)."""

    offset: int = 0
    done: bool = False
    hello_seen: bool = False


class _DirTail:
    """Incremental reader of ONE node's journal directory."""

    def __init__(self, dirpath: str):
        self.dirpath = dirpath
        self.node: Optional[str] = None
        self.cursors: Dict[str, _SegmentCursor] = {}

    def segments(self) -> List[Tuple[int, int, str]]:
        try:
            names = os.listdir(self.dirpath)
        except OSError:
            _c_stream_read_fail.inc()
            return []
        out = [(int(m.group(1)), int(m.group(2)), name)
               for name in names
               for m in (_SEGMENT_RE.match(name),) if m]
        return sorted(out)


class JournalTailer:
    """Feed an :class:`IncrementalAuditor` from journals as they grow.

    Each :meth:`poll` re-discovers journal directories under ``roots``
    (new nodes appear as their first segment lands), reads only the
    bytes appended to each segment since the previous poll, and feeds
    every complete, CRC-valid record to the auditor.  Framing validation
    matches :func:`hbbft_tpu.obs.flight.read_segment_bytes`:

    - an **incomplete** frame (header or payload cut) at the tail of the
      newest segment is a write in progress — the cursor holds and the
      poll retries it later; once a newer segment exists (rotation) or
      :meth:`finalize` runs, the leftover is a counted torn tail;
    - **corrupt** framing (absurd length, CRC mismatch, undecodable
      payload) is immediately a torn tail: the segment is closed and its
      remaining bytes skipped, exactly like the batch reader.

    Records are attributed to the incarnation in the segment filename
    (the batch reader's rule), and the node name comes from the
    segment-leading :class:`~hbbft_tpu.obs.flight.FlightHello`.
    """

    def __init__(self, roots: List[str],
                 auditor: Optional[IncrementalAuditor] = None,
                 max_read_bytes: int = 32 * 2**20):
        self.roots = list(roots)
        self.auditor = auditor if auditor is not None \
            else IncrementalAuditor()
        # one segment read is bounded per poll; a backlogged journal is
        # drained across successive polls instead of one giant read
        self.max_read_bytes = max_read_bytes
        self._dirs: Dict[str, _DirTail] = {}

    # -- discovery -----------------------------------------------------------

    def _discover(self) -> None:
        for root in self.roots:
            for d in find_journal_dirs(root):
                if d not in self._dirs:
                    self._dirs[d] = _DirTail(d)

    # -- polling -------------------------------------------------------------

    def poll(self, final: bool = False) -> int:
        """Consume newly-appended journal bytes; returns records fed.

        ``final=True`` treats every segment as rotated: a leftover
        partial frame becomes a counted torn tail instead of being
        retried (use once the run being audited has stopped)."""
        self._discover()
        fed = 0
        for d in sorted(self._dirs):
            fed += self._poll_dir(self._dirs[d], final)
        return fed

    def finalize(self) -> int:
        """One last poll with every partial tail treated as torn."""
        return self.poll(final=True)

    def result(self) -> AuditResult:
        return self.auditor.result()

    def _poll_dir(self, tail: _DirTail, final: bool) -> int:
        segs = tail.segments()
        fed = 0
        for pos, (inc, _idx, name) in enumerate(segs):
            cur = tail.cursors.setdefault(name, _SegmentCursor())
            if cur.done:
                continue
            # the newest segment may still be mid-write; anything older
            # has rotated and must parse completely or count as torn
            active = (pos == len(segs) - 1) and not final
            fed += self._consume(tail, inc, name, cur, active)
        return fed

    def _consume(self, tail: _DirTail, inc: int, name: str,
                 cur: _SegmentCursor, active: bool) -> int:
        path = os.path.join(tail.dirpath, name)
        try:
            with open(path, "rb") as fh:
                fh.seek(cur.offset)
                data = fh.read(self.max_read_bytes)
                # did the bounded read reach EOF?  only an EOF'd
                # inactive segment may be declared done/torn below
                at_eof = not data or fh.read(1) == b""
        except OSError:
            # a vanished segment (checkpoint truncation / max_segments
            # cap racing the tailer) is retired, not retried forever
            _c_stream_read_fail.inc()
            if not os.path.exists(path):
                cur.done = True
            return 0
        fed = 0
        pos = 0
        n = len(data)
        max_record = _max_record_bytes()
        torn = False
        while pos < n:
            if pos + _FRAME_HEADER.size > n:
                break  # incomplete header (mid-write or torn)
            length, crc = _FRAME_HEADER.unpack_from(data, pos)
            if length > max_record:
                torn = True  # corrupt: absurd length can never complete
                break
            if pos + 8 + length > n:
                break  # incomplete payload (mid-write or torn)
            payload = data[pos + 8: pos + 8 + length]
            if zlib.crc32(payload) != crc:
                torn = True  # corrupt: bit rot / partial overwrite
                break
            try:
                rec = wire.decode_message(
                    payload, max_bytes=max_record,
                    max_blob=len(payload))
            # hblint: disable=fault-swallowed-drop (accounted below:
            # the torn branch counts hbbft_obs_stream_torn_tails_total
            # and the auditor's torn_tails, same as the batch reader)
            except (ValueError, TypeError):
                torn = True  # corrupt: framing intact, payload not
                break
            pos += 8 + length
            fed += 1
            _c_stream_records.inc()
            if isinstance(rec, FlightHello):
                tail.node = rec.node
                self.auditor.observe_incarnation(rec.node, inc)
                cur.hello_seen = True
            elif tail.node is not None:
                self.auditor.feed(tail.node, inc, rec)
            else:
                # no hello yet for this journal (damaged first segment):
                # attribute to the directory name, the only identity left
                self.auditor.feed(os.path.basename(tail.dirpath), inc,
                                  rec)
        cur.offset += pos
        leftover = pos < n or not at_eof
        if torn or (leftover and not active and at_eof):
            # corrupt now, or an incomplete tail on a segment that can
            # no longer grow: skip the rest loudly, once
            cur.done = True
            self.auditor.add_torn()
            _c_stream_torn.inc()
        elif not leftover and not active:
            cur.done = True  # rotated segment fully consumed
        return fed


# ===========================================================================
# Structured incidents (the watchtower's view of an AuditResult)
# ===========================================================================


def extract_incidents(res: AuditResult) -> List[Dict[str, Any]]:
    """Flatten an :class:`AuditResult` into structured incident dicts.

    Each incident carries a stable ``key`` — identical evidence yields
    the identical key on every poll tick, which is what lets a live
    consumer (the watchtower) deduplicate across ticks and raise exactly
    ONE incident per underlying fault.  ``severity`` mirrors the verdict
    contribution: ``fork`` entries flip the verdict to fork, ``fault``
    to fault, ``info`` entries never change a clean verdict.
    """
    out: List[Dict[str, Any]] = []

    def add(kind: str, severity: str, subject: str, key: str,
            detail: str) -> None:
        out.append({"kind": kind, "severity": severity,
                    "subject": subject, "key": key, "detail": detail})

    if res.first_divergence:
        d = res.first_divergence
        add("fork", "fork", "cluster",
            f"fork:index={d['index']}",
            f"first divergent epoch era={d['era']} epoch={d['epoch']} "
            f"(chain index {d['index']})")
    for c in res.self_conflicts:
        add("self_fork", "fork", c["node"],
            f"self_fork:{c['node']}:index={c['index']}",
            f"{c['node']} rebuilt index {c['index']} differently: "
            f"{c['digests']}")
    for m in res.sync_mismatches:
        add("sync_mismatch", "fork", m.split(":", 1)[0].split(" ", 1)[0],
            f"sync_mismatch:{m}", m)
    for v in res.vid_inconsistencies:
        if "error" in v:
            add("vid_mismatch", "fork", "?",
                f"vid_malformed:{v['error']}", v["error"])
        else:
            add("vid_mismatch", "fork", v["root"],
                f"vid_mismatch:root={v['root']}",
                f"nodes read different payloads through committed "
                f"root {v['root'][:24]}")
    for m in res.status_mismatches:
        add("status_mismatch", "fork", m.split(":", 1)[0],
            f"status_mismatch:{m}", m)
    for e in res.equivocations:
        add("equivocation", "fault", e["sender"],
            f"equivocation:{e['sender']}:{e['kind']}:{e['slot']}",
            f"{e['sender']} {e['kind']} era={e['era']} "
            f"epoch={e['epoch']} slot={e['slot']}")
    for v in res.monotonicity_violations:
        add("monotonicity", "fault", v["node"],
            f"monotonicity:{v['node']}#{v['incarnation']}:"
            f"{v['prev']}->{v['next']}",
            f"{v['node']}#{v['incarnation']} committed {v['next']} "
            f"after {v['prev']}")
    for o in res.overload_incidents:
        kinds = " ".join(f"{k}×{n}" for k, n in o["kinds"].items())
        add("overload", "info", o["peer"],
            f"overload:{o['peer']}:{':'.join(sorted(o['kinds']))}",
            f"peer {o['peer']} — {kinds} (witnessed by "
            f"{', '.join(o['witnesses'])})")
    for e in res.restart_reproposals:
        add("restart_reproposal", "info", e["sender"],
            f"restart_reproposal:{e['sender']}:{e['kind']}:{e['slot']}",
            f"{e['sender']} {e['kind']} era={e['era']} "
            f"epoch={e['epoch']} — each value sent by a different "
            f"incarnation")
    return out

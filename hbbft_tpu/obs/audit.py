"""Cross-node forensic audit of flight-recorder journals.

``python -m hbbft_tpu.obs.audit DIR [DIR ...]`` merges the per-node
journals written by :mod:`hbbft_tpu.obs.flight` (each ``DIR`` is one
node's journal directory, or a parent holding ``node-*/`` journal
directories) and answers the operator questions a live ``/metrics``
scrape cannot:

- **causal cluster timeline** — every journaled event of every node,
  merged into one deterministic order (era, epoch, then a canonical
  event key), with sends matched to their receives by payload digest +
  target coverage.  Two audits of journals from the same deterministic
  run produce byte-identical timelines (``--timeline``);
- **agreement invariants** — all nodes' ledger-digest chains must agree
  wherever they overlap (including a node's own chain across restarts:
  replay/catch-up must rebuild the *identical* prefix), and committed
  (era, epoch) keys must be strictly monotone per node incarnation.  On
  a fork the report names the **first divergent epoch** and prints the
  surrounding event window instead of a wall of hashes;
- **equivocation evidence** — conflicting protocol messages from one
  sender for the same slot (two Merkle roots for one RBC instance, two
  Conf values for one ABA round, two decryption shares for one
  ciphertext…), reconstructed from the *receivers'* journals and keyed
  to the matching :class:`~hbbft_tpu.fault_log.FaultKind` variant, with
  the first affected epoch — the slashing-grade artifact.

Verdict: ``clean`` (all invariants hold), ``fork`` (digest chains
disagree), or ``fault`` (equivocation / monotonicity evidence, chains
intact).  Exit status 0 only on ``clean``.  Torn journal tails (crash
mid-record) are skipped loudly and counted, never fatal.

``--status HOST:PORT`` cross-checks a live node's ``/status`` chain head
+ length against its journal without needing the full chain.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.obs.audit_stream import (  # noqa: F401 — re-exported API
    _OVERLOAD_FAULT_KINDS,
    _RANK,
    _digest,
    _is_restart_reproposal,
    _parse_guard_note,
    _parse_statesync_note,
    _parse_vid_note,
    AuditResult,
    Event,
    IncrementalAuditor,
    equivocation_key,
)
from hbbft_tpu.obs.flight import (
    Journal,
    find_journal_dirs,
    read_journal,
)


# ===========================================================================
# Audit
# ===========================================================================


def audit(journals: List[Journal]) -> AuditResult:
    """Merge journals, build the timeline, verify every invariant.

    Thin batch wrapper over the incremental core: every record of every
    journal is fed to an :class:`~hbbft_tpu.obs.audit_stream.
    IncrementalAuditor` in journal order and the verdict is derived
    once — byte-identical to the historical single-pass implementation
    (regression-tested against the CLI output in test_obs_audit)."""
    aud = IncrementalAuditor()
    for j in journals:
        aud.add_node(j.node)
        for inc in j.incarnations:
            aud.observe_incarnation(j.node, inc)
        aud.add_torn(j.torn_tails)
        for inc, rec in j.records:
            aud.feed(j.node, inc, rec)
    return aud.result()


def cross_check_status(res: AuditResult, doc: Dict[str, Any]) -> None:
    """Compare a live node's ``/status`` chain head + length against its
    journal (satellite of the bounded-digest-chain work: the auditor can
    sanity-check a running node without pulling its full journal)."""
    node = doc.get("node")
    chain = res.chains.get(node)
    if chain is None:
        res.status_mismatches.append(
            f"{node}: no journaled commits to cross-check")
        return
    live_len = doc.get("chain_len", doc.get("batches", 0))
    tail = doc.get("digest_chain", [])
    offset = doc.get("digest_chain_offset", 0)
    overlap = [i for i in range(offset, offset + len(tail))
               if i in chain["commits"]]
    if not overlap:
        res.status_mismatches.append(
            f"{node}: journal (len {chain['len']}) and live chain "
            f"(len {live_len}) do not overlap")
        return
    for i in overlap:
        if chain["commits"][i][0] != tail[i - offset]:
            res.status_mismatches.append(
                f"{node}: journal digest at index {i} != live "
                f"/status digest ({chain['commits'][i][0][:16]} vs "
                f"{tail[i - offset][:16]})")
            return


# ===========================================================================
# Report
# ===========================================================================


def format_report(res: AuditResult, timeline: bool = False,
                  window: int = 4) -> str:
    lines: List[str] = []
    lines.append(f"flight audit: {len(res.nodes)} journals, "
                 f"{len(res.events)} events, "
                 f"{res.torn_tails} torn tails")
    for node in res.nodes:
        chain = res.chains.get(node)
        head = f"len={chain['len']} head={chain['head'][:16]}" \
            if chain else "no commits"
        lines.append(f"  node {node}: restarts={res.restarts[node]} "
                     f"{head}")
    if timeline:
        lines.append("-- timeline --")
        lines.extend(e.line for e in res.events)
    if res.first_divergence:
        d = res.first_divergence
        lines.append(f"FORK: first divergent epoch era={d['era']} "
                     f"epoch={d['epoch']} (chain index {d['index']})")
        for n, v in d["per_node"].items():
            lines.append(f"  {n}: era={v['era']} epoch={v['epoch']} "
                         f"digest={v['digest']}")
        lines.append("-- event window around divergence --")
        era, epoch = d["era"], d["epoch"]
        for e in res.events:
            if e.era == era and abs(e.epoch - epoch) <= window:
                lines.append("  " + e.line)
    for c in res.self_conflicts:
        lines.append(f"SELF-FORK: {c['node']} rebuilt index "
                     f"{c['index']} differently: {c['digests']}")
    for v in res.monotonicity_violations:
        lines.append(f"NON-MONOTONE: {v['node']}#{v['incarnation']} "
                     f"committed {v['next']} after {v['prev']}")
    for e in res.equivocations:
        wit = "; ".join(f"{d}<-{','.join(w)}"
                        for d, w in e["values"].items())
        lines.append(f"EQUIVOCATION: {e['sender']} {e['kind']} "
                     f"era={e['era']} epoch={e['epoch']} "
                     f"slot={e['slot']} values: {wit}")
    if res.equivocations:
        era, epoch = res.first_affected_epoch
        lines.append(f"first affected epoch: era={era} epoch={epoch}")
    for e in res.restart_reproposals:
        lines.append(f"RESTART RE-PROPOSAL (benign): {e['sender']} "
                     f"{e['kind']} era={e['era']} epoch={e['epoch']} — "
                     f"each value sent by a different incarnation")
    for j in res.sync_joins:
        v = j.get("verified_against")
        how = (f"boundary matches {v}" if v
               else "boundary uncorroborated — no overlapping journal")
        lines.append(f"STATE-SYNC JOIN: {j['node']}#{j['incarnation']} "
                     f"activated at chain index {j['index']} ({how})")
    for o in res.overload_incidents:
        kinds = " ".join(f"{k}×{n}" for k, n in o["kinds"].items())
        lines.append(f"OVERLOAD: peer {o['peer']} — {kinds} "
                     f"(witnessed by {', '.join(o['witnesses'])})")
    if res.vid_roots:
        lines.append(f"vid: {res.vid_roots} dispersed roots, "
                     f"{res.vid_corroborated} corroborated by ≥2 "
                     f"accounts")
    for v in res.vid_inconsistencies:
        if "error" in v:
            lines.append(f"VID MISMATCH: {v['error']}")
            continue
        wit = "; ".join(f"{d}<-{','.join(w)}"
                        for d, w in v["digests"].items())
        lines.append(f"VID MISMATCH: root={v['root'][:24]} — nodes "
                     f"read DIFFERENT payloads through one committed "
                     f"commitment: {wit}")
    for m in res.sync_mismatches:
        lines.append(f"SYNC MISMATCH: {m}")
    for m in res.status_mismatches:
        lines.append(f"STATUS MISMATCH: {m}")
    if res.unmatched_receives:
        lines.append(f"note: {res.unmatched_receives} receives had no "
                     f"matching journaled send (tampering, or the "
                     f"sender's journal rotated past them)")
    lines.append(f"verdict: {res.verdict}")
    return "\n".join(lines) + "\n"


def run_audit(paths: List[str]) -> Tuple[AuditResult, List[Journal]]:
    dirs: List[str] = []
    for p in paths:
        found = find_journal_dirs(p)
        if not found:
            raise FileNotFoundError(f"no journal segments under {p!r}")
        dirs.extend(found)
    journals = [read_journal(d) for d in dirs]
    return audit(journals), journals


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.audit", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+", metavar="DIR",
                    help="journal directories (or parents of node-*/)")
    ap.add_argument("--timeline", action="store_true",
                    help="print the full merged causal timeline")
    ap.add_argument("--json", action="store_true",
                    help="print the verdict document as JSON")
    ap.add_argument("--window", type=int, default=4,
                    help="epochs of context around a divergence")
    ap.add_argument("--status", action="append", default=[],
                    metavar="HOST:PORT",
                    help="cross-check a live node's /status chain head")
    ap.add_argument("--critpath", action="store_true",
                    help="append the per-tx critical-path report "
                         "(obs.critpath) over the same journals")
    args = ap.parse_args(argv)
    try:
        res, _journals = run_audit(args.paths)
    # hblint: disable=fault-swallowed-drop (CLI entry: exit status 2 is
    # the accounting — there is no registry in an offline audit run)
    except (FileNotFoundError, OSError) as exc:
        print(f"audit: {exc}", file=sys.stderr)
        return 2
    for target in args.status:
        from hbbft_tpu.obs.http import http_get

        host, _, port = target.rpartition(":")
        try:
            doc = json.loads(http_get(host or "127.0.0.1", int(port),
                                      "/status"))
        # hblint: disable=fault-swallowed-drop (accounted: the appended
        # status_mismatch flips the verdict to fork and the exit to 1)
        except (OSError, ValueError) as exc:
            res.status_mismatches.append(f"{target}: unreachable "
                                         f"({exc!r})")
            continue
        cross_check_status(res, doc)
    cp_report = None
    if args.critpath:
        from hbbft_tpu.obs import critpath as _critpath

        dirs: List[str] = []
        for p in args.paths:
            dirs.extend(find_journal_dirs(p))
        cp_report = _critpath.build_report(sorted(dirs))
    if args.json:
        doc = res.as_dict()
        if cp_report is not None:
            doc["critical_path"] = cp_report
        print(json.dumps(doc, sort_keys=True))
    else:
        sys.stdout.write(format_report(res, timeline=args.timeline,
                                       window=args.window))
        if cp_report is not None:
            print("-- critical path --")
            print(_critpath.render(cp_report))
    return 0 if res.verdict == "clean" else 1


if __name__ == "__main__":
    sys.exit(main())

"""Cross-node forensic audit of flight-recorder journals.

``python -m hbbft_tpu.obs.audit DIR [DIR ...]`` merges the per-node
journals written by :mod:`hbbft_tpu.obs.flight` (each ``DIR`` is one
node's journal directory, or a parent holding ``node-*/`` journal
directories) and answers the operator questions a live ``/metrics``
scrape cannot:

- **causal cluster timeline** — every journaled event of every node,
  merged into one deterministic order (era, epoch, then a canonical
  event key), with sends matched to their receives by payload digest +
  target coverage.  Two audits of journals from the same deterministic
  run produce byte-identical timelines (``--timeline``);
- **agreement invariants** — all nodes' ledger-digest chains must agree
  wherever they overlap (including a node's own chain across restarts:
  replay/catch-up must rebuild the *identical* prefix), and committed
  (era, epoch) keys must be strictly monotone per node incarnation.  On
  a fork the report names the **first divergent epoch** and prints the
  surrounding event window instead of a wall of hashes;
- **equivocation evidence** — conflicting protocol messages from one
  sender for the same slot (two Merkle roots for one RBC instance, two
  Conf values for one ABA round, two decryption shares for one
  ciphertext…), reconstructed from the *receivers'* journals and keyed
  to the matching :class:`~hbbft_tpu.fault_log.FaultKind` variant, with
  the first affected epoch — the slashing-grade artifact.

Verdict: ``clean`` (all invariants hold), ``fork`` (digest chains
disagree), or ``fault`` (equivocation / monotonicity evidence, chains
intact).  Exit status 0 only on ``clean``.  Torn journal tails (crash
mid-record) are skipped loudly and counted, never fatal.

``--status HOST:PORT`` cross-checks a live node's ``/status`` chain head
+ length against its journal without needing the full chain.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.fault_log import FaultKind, equivocation_kinds
from hbbft_tpu.obs.flight import (
    FlightCommit,
    FlightFault,
    FlightMsg,
    FlightNote,
    FlightSpan,
    Journal,
    find_journal_dirs,
    read_journal,
    target_covers,
)
from hbbft_tpu.protocols import wire

#: timeline ordering rank per record family (notes lead their epoch,
#: then sends/receives, commits close it, spans/faults trail as derived)
_RANK = {"note": 0, "msg": 1, "commit": 2, "span": 3, "fault": 4}


#: FlightFault kinds that are protocol-layer overload evidence (flood
#: budgets engaging), as opposed to protocol misbehavior of other shapes
_OVERLOAD_FAULT_KINDS = frozenset({
    "FutureEpochFlood", "SubsetMessageFlood",
})


def _parse_guard_note(detail: str) -> Optional[Dict[str, str]]:
    """``kind=K peer=P …`` → {kind, peer[, claimed]} (the runtime's
    overload-guard journal format; see NodeRuntime._process_guard_event).
    ``auth_fail`` notes carry both sides of a spoof: ``peer`` is the
    ATTACKER's socket endpoint, ``claimed`` the impersonated identity —
    keeping them separate is what lets the incident report blame the
    endpoint without smearing the victim."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    if "kind" not in fields or "peer" not in fields:
        return None
    out = {"kind": fields["kind"], "peer": fields["peer"]}
    if "claimed" in fields:
        out["claimed"] = fields["claimed"]
    return out


def _parse_statesync_note(detail: str) -> Optional[Dict[str, Any]]:
    """``index=N head=HEX`` → {index, head} (the boundary a snapshot
    joiner's runtime journals at activation)."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    try:
        return {"index": int(fields["index"]), "head": fields["head"]}
    # hblint: disable=fault-swallowed-drop (accounted at the caller: a
    # None return lands in sync_mismatches and flips the verdict to fork)
    except (KeyError, ValueError):
        return None


def _parse_vid_note(detail: str) -> Optional[Dict[str, str]]:
    """``root=HEX … payload_sha3=D`` → field dict (the runtime's VID
    journal format: ``vid_cert`` notes from the proposer anchor the
    payload digest behind a dispersed root; ``vid_retrieved`` notes from
    every resolver must corroborate it)."""
    fields = dict(
        part.split("=", 1) for part in detail.split() if "=" in part
    )
    if "root" not in fields or "payload_sha3" not in fields:
        return None
    return fields


def _digest(payload: bytes) -> str:
    return hashlib.sha3_256(payload).hexdigest()[:16]


# ===========================================================================
# Equivocation slots
# ===========================================================================


def equivocation_key(msg: Any
                     ) -> Optional[Tuple[Tuple, bytes, FaultKind]]:
    """``(slot, value, FaultKind)`` for messages where one sender emitting
    two *different* values for the same slot is proof of equivocation;
    ``None`` for messages that may legitimately repeat with different
    values (BVal/Aux vote for both sides honestly, EpochStarted
    re-announces).  The slot includes everything that scopes the value;
    the sender is supplied by the caller."""
    from hbbft_tpu.protocols.binary_agreement import (
        CoinMsg, ConfMsg, TermMsg,
    )
    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
    )
    from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap
    from hbbft_tpu.protocols.honey_badger import (
        DecryptionShareWrap, SubsetWrap,
    )
    from hbbft_tpu.protocols.sender_queue import AlgoMessage
    from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap

    era = 0
    if isinstance(msg, AlgoMessage):
        msg = msg.msg
    if isinstance(msg, HbWrap):
        era = msg.era
        msg = msg.msg
    if isinstance(msg, DecryptionShareWrap):
        share = msg.msg.share
        return ((era, msg.epoch, "decrypt", repr(msg.proposer_id)),
                share.to_bytes(), FaultKind.MultipleDecryptionShares)
    if not isinstance(msg, SubsetWrap):
        return None
    epoch = msg.epoch
    inner = msg.msg
    if isinstance(inner, BroadcastWrap):
        proposer = repr(inner.proposer_id)
        m = inner.msg
        rules = (
            (ValueMsg, "value", FaultKind.MultipleValues),
            (EchoMsg, "echo", FaultKind.MultipleEchos),
            (EchoHashMsg, "echo_hash", FaultKind.MultipleEchoHashes),
            (CanDecodeMsg, "can_decode", FaultKind.MultipleCanDecodes),
            (ReadyMsg, "ready", FaultKind.MultipleReadys),
        )
        for cls, tag, kind in rules:
            if isinstance(m, cls):
                root = m.proof.root_hash if isinstance(
                    m, (ValueMsg, EchoMsg)) else m.root
                return ((era, epoch, "rbc", proposer, tag), root, kind)
        return None
    if isinstance(inner, AgreementWrap):
        proposer = repr(inner.proposer_id)
        m = inner.msg
        if isinstance(m, ConfMsg):
            value = bytes([(False in m.values)
                           | ((True in m.values) << 1)])
            return ((era, epoch, "aba", proposer, "conf", m.epoch),
                    value, FaultKind.MultipleConf)
        if isinstance(m, TermMsg):
            return ((era, epoch, "aba", proposer, "term"),
                    b"\x01" if m.value else b"\x00",
                    FaultKind.MultipleTerm)
        if isinstance(m, CoinMsg):
            inner_msg = m.msg
            share = getattr(inner_msg, "share", None)
            if share is not None:
                return ((era, epoch, "aba", proposer, "coin", m.epoch),
                        share.to_bytes(),
                        FaultKind.MultipleSignatureShares)
    return None


# ===========================================================================
# Audit
# ===========================================================================


@dataclass
class Event:
    """One timeline entry (sort-stable canonical key + display line)."""

    era: int
    epoch: int
    rank: int
    key: Tuple
    line: str


@dataclass
class AuditResult:
    nodes: List[str] = field(default_factory=list)
    events: List[Event] = field(default_factory=list)
    chains: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    first_divergence: Optional[Dict[str, Any]] = None
    self_conflicts: List[Dict[str, Any]] = field(default_factory=list)
    monotonicity_violations: List[Dict[str, Any]] = field(
        default_factory=list)
    equivocations: List[Dict[str, Any]] = field(default_factory=list)
    unmatched_receives: int = 0
    decode_failures: int = 0
    torn_tails: int = 0
    restarts: Dict[str, int] = field(default_factory=dict)
    status_mismatches: List[str] = field(default_factory=list)
    # membership lifecycle: nodes that activated from a state-sync
    # snapshot (the journal's ``statesync`` note declares the claimed
    # chain boundary), with the boundary verified against every other
    # journal's digest at the preceding index
    sync_joins: List[Dict[str, Any]] = field(default_factory=list)
    sync_mismatches: List[str] = field(default_factory=list)
    # conflicting slot values that attribute cleanly to DIFFERENT
    # incarnations of the sender (its own journal shows each value sent
    # exactly once, by a different process life): the expected amnesia
    # artifact of a crash-restart without persistence re-proposing into
    # already-decided epochs — reported, but not a fault verdict.  True
    # equivocation (two values inside one incarnation, or a value the
    # sender never journaled sending — the tampering shape) still is.
    restart_reproposals: List[Dict[str, Any]] = field(
        default_factory=list)
    # VID cert-vs-retrieval corroboration: every ``vid_retrieved`` note's
    # payload digest must agree with the proposer's ``vid_cert`` anchor
    # and with every other resolver of the same root.  Two digests behind
    # one committed root is a content fork — the ordered commitment was
    # unambiguous but nodes read different payloads through it.
    # Uncorroborated roots (proposer journal rotated, no retrieval yet)
    # are benign and merely counted.
    vid_roots: int = 0
    vid_corroborated: int = 0
    vid_inconsistencies: List[Dict[str, Any]] = field(
        default_factory=list)
    # resource-exhaustion forensics: journaled ``guard`` notes (ingress
    # throttle escalations, SenderQueue backlog evictions, hello rejects
    # — written by the runtime's overload defense) plus protocol-layer
    # flood faults (FutureEpochFlood / SubsetMessageFlood), aggregated
    # per OFFENDING peer so an incident attributes to the spamming node.
    # Defense working as designed is not a fault verdict.
    overload_incidents: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def first_affected_epoch(self) -> Optional[Tuple[int, int]]:
        keys = [(e["era"], e["epoch"]) for e in self.equivocations]
        return min(keys) if keys else None

    @property
    def verdict(self) -> str:
        if self.first_divergence or self.self_conflicts \
                or self.status_mismatches or self.sync_mismatches \
                or self.vid_inconsistencies:
            return "fork"
        if self.equivocations or self.monotonicity_violations:
            return "fault"
        return "clean"

    def as_dict(self) -> Dict[str, Any]:
        fa = self.first_affected_epoch
        return {
            "verdict": self.verdict,
            "nodes": self.nodes,
            "restarts": self.restarts,
            "torn_tails": self.torn_tails,
            "decode_failures": self.decode_failures,
            "unmatched_receives": self.unmatched_receives,
            "chains": {
                n: {"head": c["head"], "len": c["len"]}
                for n, c in self.chains.items()
            },
            "first_divergence": self.first_divergence,
            "self_conflicts": self.self_conflicts,
            "monotonicity_violations": self.monotonicity_violations,
            "equivocations": self.equivocations,
            "first_affected_epoch": list(fa) if fa else None,
            "status_mismatches": self.status_mismatches,
            "sync_joins": self.sync_joins,
            "sync_mismatches": self.sync_mismatches,
            "restart_reproposals": self.restart_reproposals,
            "overload_incidents": self.overload_incidents,
            "vid_roots": self.vid_roots,
            "vid_corroborated": self.vid_corroborated,
            "vid_inconsistencies": self.vid_inconsistencies,
        }


def audit(journals: List[Journal]) -> AuditResult:
    """Merge journals, build the timeline, verify every invariant."""
    res = AuditResult()
    res.torn_tails = sum(j.torn_tails for j in journals)
    res.nodes = [j.node for j in journals]
    res.restarts = {j.node: max(0, j.starts - 1) for j in journals}

    # -- outbound index: sender node → payload digest → [(inc, rec)] ---------
    out_index: Dict[str, Dict[str, List[Tuple[int, FlightMsg]]]] = {}
    for j in journals:
        idx = out_index.setdefault(j.node, {})
        for inc, rec in j.records:
            if isinstance(rec, FlightMsg) and rec.direction == "out" \
                    and rec.payload:
                idx.setdefault(_digest(rec.payload), []).append(
                    (inc, rec))

    # -- walk every record: timeline + commits + equivocation slots ----------
    # slots[(sender, slot)] = {value_digest: sorted set of witness nodes}
    slots: Dict[Tuple, Dict[str, Any]] = {}
    # the sender's own account: per slot, which incarnation(s) journaled
    # SENDING each value — what separates a crash-restart re-proposal
    # from equivocation/tampering
    slot_sends: Dict[Tuple, Dict[str, set]] = {}
    commits: Dict[str, Dict[int, Tuple[str, int, int, int]]] = {}
    # overload[peer] = {"kinds": {kind: count}, "witnesses": set}
    overload: Dict[str, Dict[str, Any]] = {}
    # vid[root] = {payload_sha3: {"cert:<node>" | "retr:<node>", ...}}
    vid: Dict[str, Dict[str, set]] = {}
    vid_anchored: set = set()  # roots with at least one vid_cert note

    def _overload_hit(peer: str, kind: str, witness: str,
                      claimed: Optional[str] = None) -> None:
        entry = overload.setdefault(
            peer, {"kinds": {}, "witnesses": set(), "claimed": set()})
        entry["kinds"][kind] = entry["kinds"].get(kind, 0) + 1
        entry["witnesses"].add(witness)
        if claimed is not None:
            entry["claimed"].add(claimed)

    for j in journals:
        node = j.node
        per_index = commits.setdefault(node, {})
        last_key: Dict[int, Tuple[int, int]] = {}  # inc → last (era, ep)
        for inc, rec in j.records:
            if isinstance(rec, FlightMsg):
                d = _digest(rec.payload) if rec.payload else "-"
                if rec.direction == "in":
                    line = (f"era={rec.era} ep={rec.epoch} msg "
                            f"{rec.mtype} {d} {rec.peer}->{node} "
                            f"in@{node}#{inc}.{rec.seq}")
                else:
                    line = (f"era={rec.era} ep={rec.epoch} msg "
                            f"{rec.mtype} {d} {node}->({rec.peer}) "
                            f"out@{node}#{inc}.{rec.seq}")
                res.events.append(Event(
                    rec.era, rec.epoch, _RANK["msg"],
                    (rec.mtype, d, 0 if rec.direction == "out" else 1,
                     node, inc, rec.seq), line))
                if rec.direction == "out" and rec.payload:
                    # the sender's own account of what it emitted for
                    # each equivocation slot, tagged with the process
                    # incarnation that sent it
                    try:
                        msg = wire.decode_message(rec.payload)
                    except (ValueError, TypeError):
                        res.decode_failures += 1
                        continue
                    eq = equivocation_key(msg)
                    if eq is not None:
                        slot, value, kind = eq
                        slot_sends.setdefault(
                            (node, slot, kind), {}).setdefault(
                            _digest(value), set()).add(inc)
                if rec.direction != "in" or not rec.payload:
                    continue
                # match the receive to a journaled send
                sender = rec.peer
                if sender in out_index:
                    outs = out_index[sender].get(d, ())
                    if not any(target_covers(o.peer, node)
                               for _i, o in outs):
                        res.unmatched_receives += 1
                # equivocation slots are receiver-side evidence
                try:
                    msg = wire.decode_message(rec.payload)
                except (ValueError, TypeError):
                    res.decode_failures += 1
                    continue
                eq = equivocation_key(msg)
                if eq is not None:
                    slot, value, kind = eq
                    vals = slots.setdefault((sender, slot, kind), {})
                    vals.setdefault(
                        _digest(value), set()).add(node)
            elif isinstance(rec, FlightCommit):
                dig = rec.digest.hex()
                res.events.append(Event(
                    rec.era, rec.epoch, _RANK["commit"],
                    ("commit", rec.index, node, inc, rec.seq),
                    f"era={rec.era} ep={rec.epoch} commit "
                    f"idx={rec.index} {dig[:16]} @{node}#{inc}"))
                prev = per_index.get(rec.index)
                if prev is not None and prev[0] != dig:
                    res.self_conflicts.append({
                        "node": node, "index": rec.index,
                        "digests": sorted((prev[0][:16], dig[:16])),
                    })
                else:
                    per_index[rec.index] = (dig, rec.era, rec.epoch,
                                            inc)
                last = last_key.get(inc)
                if last is not None and (rec.era, rec.epoch) <= last:
                    res.monotonicity_violations.append({
                        "node": node, "incarnation": inc,
                        "prev": list(last),
                        "next": [rec.era, rec.epoch],
                    })
                last_key[inc] = (rec.era, rec.epoch)
            elif isinstance(rec, FlightFault):
                res.events.append(Event(
                    rec.era, rec.epoch, _RANK["fault"],
                    ("fault", rec.kind, rec.node, node, inc, rec.seq),
                    f"era={rec.era} ep={rec.epoch} fault {rec.kind} "
                    f"by {rec.node} seen@{node}#{inc}"))
                if rec.kind in _OVERLOAD_FAULT_KINDS:
                    _overload_hit(rec.node, rec.kind, node)
            elif isinstance(rec, FlightSpan):
                rnd = "-" if rec.round is None else rec.round
                res.events.append(Event(
                    rec.era, rec.epoch, _RANK["span"],
                    ("span", rec.name, rnd, node, inc, rec.seq),
                    f"era={rec.era} ep={rec.epoch} span {rec.name} "
                    f"r={rnd} n={rec.count} @{node}#{inc}"))
            elif isinstance(rec, FlightNote):
                res.events.append(Event(
                    0, 0, _RANK["note"],
                    ("note", rec.kind, node, inc, rec.seq),
                    f"note {rec.kind} {rec.detail} @{node}#{inc}"))
                if rec.kind == "statesync":
                    join = _parse_statesync_note(rec.detail)
                    if join is None:
                        res.sync_mismatches.append(
                            f"{node}#{inc}: malformed statesync note "
                            f"{rec.detail!r}")
                    else:
                        join.update({"node": node, "incarnation": inc})
                        res.sync_joins.append(join)
                elif rec.kind == "guard":
                    hit = _parse_guard_note(rec.detail)
                    if hit is not None:
                        _overload_hit(hit["peer"], hit["kind"], node,
                                      hit.get("claimed"))
                elif rec.kind in ("vid_cert", "vid_retrieved"):
                    fields = _parse_vid_note(rec.detail)
                    if fields is None:
                        res.vid_inconsistencies.append({
                            "root": "?",
                            "error": f"malformed {rec.kind} note "
                                     f"{rec.detail!r} @{node}#{inc}",
                        })
                        continue
                    sha3 = fields["payload_sha3"]
                    if sha3 == "none":
                        # failed retrieval — already surfaced through
                        # the vid_mismatch/vid_exhausted notes and the
                        # proposer fault; no digest to corroborate
                        continue
                    tag = ("cert" if rec.kind == "vid_cert"
                           else "retr")
                    vid.setdefault(fields["root"], {}).setdefault(
                        sha3, set()).add(f"{tag}:{node}")
                    if rec.kind == "vid_cert":
                        vid_anchored.add(fields["root"])
    res.events.sort(key=lambda e: (e.era, e.epoch, e.rank, e.key))
    # resource-exhaustion attribution: most-implicated peer first
    res.overload_incidents = [
        {
            "peer": peer,
            "kinds": dict(sorted(entry["kinds"].items())),
            "witnesses": sorted(entry["witnesses"]),
            "events": sum(entry["kinds"].values()),
            # spoof attribution: the identities this endpoint CLAIMED
            # while failing authentication (distinct from "peer" — the
            # impersonated validator is the victim, not the attacker)
            **({"claimed_identities": sorted(entry["claimed"])}
               if entry["claimed"] else {}),
        }
        for peer, entry in sorted(
            overload.items(),
            key=lambda kv: (-sum(kv[1]["kinds"].values()), kv[0]),
        )
    ]

    # -- VID cert-vs-retrieval consistency -----------------------------------
    # One root, one payload: the proposer's vid_cert digest and every
    # resolver's vid_retrieved digest must be THE same sha3.  A root only
    # counts as corroborated when at least two independent accounts
    # agree (cert + a retrieval, or two retrievals); a lone account is
    # benign but proves nothing.
    res.vid_roots = len(vid)
    for root in sorted(vid):
        digests = vid[root]
        if len(digests) > 1:
            res.vid_inconsistencies.append({
                "root": root,
                "anchored": root in vid_anchored,
                "digests": {d: sorted(w)
                            for d, w in sorted(digests.items())},
            })
        elif sum(len(w) for w in digests.values()) >= 2:
            res.vid_corroborated += 1

    # -- digest-chain agreement ----------------------------------------------
    for node, per_index in commits.items():
        if per_index:
            top = max(per_index)
            res.chains[node] = {
                "len": top + 1,
                "head": per_index[top][0],
                "commits": per_index,
            }
    all_indices = sorted({i for c in commits.values() for i in c})
    for i in all_indices:
        present = {n: c[i] for n, c in commits.items() if i in c}
        if len({v[0] for v in present.values()}) > 1:
            res.first_divergence = {
                "index": i,
                "per_node": {
                    n: {"digest": v[0][:16], "era": v[1], "epoch": v[2]}
                    for n, v in sorted(present.items())
                },
                "era": min(v[1] for v in present.values()),
                "epoch": min(v[2] for v in present.values()),
            }
            break

    # -- membership-lifecycle boundaries -------------------------------------
    # A state-sync join claims "my chain starts at index k with head H".
    # That claim must match what the rest of the cluster committed: any
    # journal holding index k−1 must hold digest H there.  A joiner whose
    # claimed boundary nobody can corroborate stays unverified (benign:
    # donors' journals may have rotated past it); a CONTRADICTED boundary
    # is a fork.
    for join in res.sync_joins:
        idx, head = join["index"], join["head"]
        verified = None
        for other, per_index in commits.items():
            prev = per_index.get(idx - 1)
            if prev is None:
                continue
            if prev[0] == head:
                verified = other
            else:
                res.sync_mismatches.append(
                    f"{join['node']} joined claiming chain[{idx - 1}] "
                    f"= {head[:16]} but {other} committed "
                    f"{prev[0][:16]} there")
                verified = None
                break
        join["verified_against"] = verified

    # -- equivocation evidence ----------------------------------------------
    eq_kinds = equivocation_kinds()
    for (sender, slot, kind), vals in sorted(
            slots.items(), key=lambda kv: repr(kv[0])):
        if len(vals) < 2:
            continue
        assert kind in eq_kinds
        entry = {
            "sender": sender,
            "kind": kind.name,
            "era": slot[0],
            "epoch": slot[1],
            "slot": repr(slot),
            "values": {d: sorted(w) for d, w in sorted(vals.items())},
        }
        if _is_restart_reproposal(vals, slot_sends.get(
                (sender, slot, kind))):
            res.restart_reproposals.append(entry)
        else:
            res.equivocations.append(entry)
    return res


def _is_restart_reproposal(vals: Dict[str, Any],
                           sent: Optional[Dict[str, set]]) -> bool:
    """Do the conflicting values attribute cleanly to different process
    incarnations of the sender?  Requires the sender's own journal to
    show EVERY witnessed value being sent, each by exactly one
    incarnation, all incarnations distinct — the amnesia shape of a
    crash-restart re-proposing into already-decided epochs.  Anything
    less (a value the sender never journaled — tampering; two values in
    one incarnation — equivocation; rotated-away sender evidence) stays
    slashing-grade."""
    if sent is None:
        return False
    if set(vals) - set(sent):
        return False
    incs = [sent[d] for d in vals]
    if any(len(s) != 1 for s in incs):
        return False
    flat = [next(iter(s)) for s in incs]
    return len(set(flat)) == len(flat)


def cross_check_status(res: AuditResult, doc: Dict[str, Any]) -> None:
    """Compare a live node's ``/status`` chain head + length against its
    journal (satellite of the bounded-digest-chain work: the auditor can
    sanity-check a running node without pulling its full journal)."""
    node = doc.get("node")
    chain = res.chains.get(node)
    if chain is None:
        res.status_mismatches.append(
            f"{node}: no journaled commits to cross-check")
        return
    live_len = doc.get("chain_len", doc.get("batches", 0))
    tail = doc.get("digest_chain", [])
    offset = doc.get("digest_chain_offset", 0)
    overlap = [i for i in range(offset, offset + len(tail))
               if i in chain["commits"]]
    if not overlap:
        res.status_mismatches.append(
            f"{node}: journal (len {chain['len']}) and live chain "
            f"(len {live_len}) do not overlap")
        return
    for i in overlap:
        if chain["commits"][i][0] != tail[i - offset]:
            res.status_mismatches.append(
                f"{node}: journal digest at index {i} != live "
                f"/status digest ({chain['commits'][i][0][:16]} vs "
                f"{tail[i - offset][:16]})")
            return


# ===========================================================================
# Report
# ===========================================================================


def format_report(res: AuditResult, timeline: bool = False,
                  window: int = 4) -> str:
    lines: List[str] = []
    lines.append(f"flight audit: {len(res.nodes)} journals, "
                 f"{len(res.events)} events, "
                 f"{res.torn_tails} torn tails")
    for node in res.nodes:
        chain = res.chains.get(node)
        head = f"len={chain['len']} head={chain['head'][:16]}" \
            if chain else "no commits"
        lines.append(f"  node {node}: restarts={res.restarts[node]} "
                     f"{head}")
    if timeline:
        lines.append("-- timeline --")
        lines.extend(e.line for e in res.events)
    if res.first_divergence:
        d = res.first_divergence
        lines.append(f"FORK: first divergent epoch era={d['era']} "
                     f"epoch={d['epoch']} (chain index {d['index']})")
        for n, v in d["per_node"].items():
            lines.append(f"  {n}: era={v['era']} epoch={v['epoch']} "
                         f"digest={v['digest']}")
        lines.append("-- event window around divergence --")
        era, epoch = d["era"], d["epoch"]
        for e in res.events:
            if e.era == era and abs(e.epoch - epoch) <= window:
                lines.append("  " + e.line)
    for c in res.self_conflicts:
        lines.append(f"SELF-FORK: {c['node']} rebuilt index "
                     f"{c['index']} differently: {c['digests']}")
    for v in res.monotonicity_violations:
        lines.append(f"NON-MONOTONE: {v['node']}#{v['incarnation']} "
                     f"committed {v['next']} after {v['prev']}")
    for e in res.equivocations:
        wit = "; ".join(f"{d}<-{','.join(w)}"
                        for d, w in e["values"].items())
        lines.append(f"EQUIVOCATION: {e['sender']} {e['kind']} "
                     f"era={e['era']} epoch={e['epoch']} "
                     f"slot={e['slot']} values: {wit}")
    if res.equivocations:
        era, epoch = res.first_affected_epoch
        lines.append(f"first affected epoch: era={era} epoch={epoch}")
    for e in res.restart_reproposals:
        lines.append(f"RESTART RE-PROPOSAL (benign): {e['sender']} "
                     f"{e['kind']} era={e['era']} epoch={e['epoch']} — "
                     f"each value sent by a different incarnation")
    for j in res.sync_joins:
        v = j.get("verified_against")
        how = (f"boundary matches {v}" if v
               else "boundary uncorroborated — no overlapping journal")
        lines.append(f"STATE-SYNC JOIN: {j['node']}#{j['incarnation']} "
                     f"activated at chain index {j['index']} ({how})")
    for o in res.overload_incidents:
        kinds = " ".join(f"{k}×{n}" for k, n in o["kinds"].items())
        lines.append(f"OVERLOAD: peer {o['peer']} — {kinds} "
                     f"(witnessed by {', '.join(o['witnesses'])})")
    if res.vid_roots:
        lines.append(f"vid: {res.vid_roots} dispersed roots, "
                     f"{res.vid_corroborated} corroborated by ≥2 "
                     f"accounts")
    for v in res.vid_inconsistencies:
        if "error" in v:
            lines.append(f"VID MISMATCH: {v['error']}")
            continue
        wit = "; ".join(f"{d}<-{','.join(w)}"
                        for d, w in v["digests"].items())
        lines.append(f"VID MISMATCH: root={v['root'][:24]} — nodes "
                     f"read DIFFERENT payloads through one committed "
                     f"commitment: {wit}")
    for m in res.sync_mismatches:
        lines.append(f"SYNC MISMATCH: {m}")
    for m in res.status_mismatches:
        lines.append(f"STATUS MISMATCH: {m}")
    if res.unmatched_receives:
        lines.append(f"note: {res.unmatched_receives} receives had no "
                     f"matching journaled send (tampering, or the "
                     f"sender's journal rotated past them)")
    lines.append(f"verdict: {res.verdict}")
    return "\n".join(lines) + "\n"


def run_audit(paths: List[str]) -> Tuple[AuditResult, List[Journal]]:
    dirs: List[str] = []
    for p in paths:
        found = find_journal_dirs(p)
        if not found:
            raise FileNotFoundError(f"no journal segments under {p!r}")
        dirs.extend(found)
    journals = [read_journal(d) for d in dirs]
    return audit(journals), journals


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.audit", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+", metavar="DIR",
                    help="journal directories (or parents of node-*/)")
    ap.add_argument("--timeline", action="store_true",
                    help="print the full merged causal timeline")
    ap.add_argument("--json", action="store_true",
                    help="print the verdict document as JSON")
    ap.add_argument("--window", type=int, default=4,
                    help="epochs of context around a divergence")
    ap.add_argument("--status", action="append", default=[],
                    metavar="HOST:PORT",
                    help="cross-check a live node's /status chain head")
    ap.add_argument("--critpath", action="store_true",
                    help="append the per-tx critical-path report "
                         "(obs.critpath) over the same journals")
    args = ap.parse_args(argv)
    try:
        res, _journals = run_audit(args.paths)
    # hblint: disable=fault-swallowed-drop (CLI entry: exit status 2 is
    # the accounting — there is no registry in an offline audit run)
    except (FileNotFoundError, OSError) as exc:
        print(f"audit: {exc}", file=sys.stderr)
        return 2
    for target in args.status:
        from hbbft_tpu.obs.http import http_get

        host, _, port = target.rpartition(":")
        try:
            doc = json.loads(http_get(host or "127.0.0.1", int(port),
                                      "/status"))
        # hblint: disable=fault-swallowed-drop (accounted: the appended
        # status_mismatch flips the verdict to fork and the exit to 1)
        except (OSError, ValueError) as exc:
            res.status_mismatches.append(f"{target}: unreachable "
                                         f"({exc!r})")
            continue
        cross_check_status(res, doc)
    cp_report = None
    if args.critpath:
        from hbbft_tpu.obs import critpath as _critpath

        dirs: List[str] = []
        for p in args.paths:
            dirs.extend(find_journal_dirs(p))
        cp_report = _critpath.build_report(sorted(dirs))
    if args.json:
        doc = res.as_dict()
        if cp_report is not None:
            doc["critical_path"] = cp_report
        print(json.dumps(doc, sort_keys=True))
    else:
        sys.stdout.write(format_report(res, timeline=args.timeline,
                                       window=args.window))
        if cp_report is not None:
            print("-- critical path --")
            print(_critpath.render(cp_report))
    return 0 if res.verdict == "clean" else 1


if __name__ == "__main__":
    sys.exit(main())

"""Minimal asyncio HTTP/1.1 exposition endpoint (GET-only, no deps).

One :class:`ObsServer` per node serves:

- ``GET /metrics`` — Prometheus text format 0.0.4 from the node's registry;
- ``GET /status``  — the runtime's JSON status document;
- ``GET /spans``   — finished epoch-phase spans as JSONL
  (``application/x-ndjson``), newest-bounded (see ``SpanTracer.max_spans``);
- ``GET /flight``  — the flight recorder's in-memory record tail as JSONL
  (payloads summarized as digest+size; the on-disk journal has the bytes);
- ``GET /trace``   — the tail filtered to per-tx causal trace records
  (``obs.trace``), tids in hex — grep a tid across nodes live;
- ``GET /health``  — the runtime's machine-readable health/headroom
  document (status + per-lever headroom fractions; what the watchtower
  polls and the future adaptive controller will consume);
- ``GET /perf``    — the performance plane's flame-style profile +
  headroom document (``obs.perf.PerfPlane.perf_doc``): per-layer
  utilization, per-segment busy time over the retained window, and the
  raw sampling-window series.

Deliberately tiny: request line + headers are read with a hard cap and a
timeout, responses are ``Connection: close``, and anything but a known GET
path is a 404/405.  This is a diagnostics port with the same trust model as
the transport hello (identification, not authentication) — bind it to
localhost or a private fabric, like the consensus port.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from typing import Callable, Dict, Optional, Tuple

Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.obs")

_MAX_HEADER_BYTES = 8192
_REQUEST_TIMEOUT_S = 5.0


class ObsServer:
    """Serve one registry (+ optional status/spans providers) over HTTP."""

    def __init__(self, registry, status_fn: Optional[Callable[[], dict]] = None,
                 spans_fn: Optional[Callable[[], str]] = None,
                 flight_fn: Optional[Callable[[], str]] = None,
                 trace_fn: Optional[Callable[[], str]] = None,
                 health_fn: Optional[Callable[[], dict]] = None,
                 perf_fn: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.status_fn = status_fn
        self.spans_fn = spans_fn
        self.flight_fn = flight_fn
        self.trace_fn = trace_fn
        self.health_fn = health_fn
        self.perf_fn = perf_fn
        self._c_dropped = registry.counter(
            "hbbft_obs_http_dropped_requests_total",
            "obs-endpoint requests dropped (malformed, timed out, or "
            "the client vanished mid-response)")
        self._server: Optional[asyncio.base_events.Server] = None
        self.addr: Optional[Addr] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        self._server = await asyncio.start_server(
            self._handle, host=host, port=port
        )
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request handling ----------------------------------------------------

    def _route(self, path: str) -> Tuple[int, str, str]:
        """(status code, content type, body)."""
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    self.registry.render_prometheus())
        if path == "/status":
            doc = self.status_fn() if self.status_fn is not None else {}
            return (200, "application/json", json.dumps(doc))
        if path == "/spans":
            body = self.spans_fn() if self.spans_fn is not None else ""
            return (200, "application/x-ndjson", body)
        if path == "/flight":
            body = self.flight_fn() if self.flight_fn is not None else ""
            return (200, "application/x-ndjson", body)
        if path == "/trace":
            body = self.trace_fn() if self.trace_fn is not None else ""
            return (200, "application/x-ndjson", body)
        if path == "/health":
            doc = self.health_fn() if self.health_fn is not None else {}
            return (200, "application/json", json.dumps(doc))
        if path == "/perf":
            doc = self.perf_fn() if self.perf_fn is not None else {}
            return (200, "application/json", json.dumps(doc))
        return (404, "text/plain; charset=utf-8",
                "not found; try /metrics /status /spans /flight /trace "
                "/health /perf\n")

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), _REQUEST_TIMEOUT_S
            )
            if len(request) > _MAX_HEADER_BYTES:
                raise ValueError("oversized request header")
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            parts = line.split()
            if len(parts) < 2:
                raise ValueError(f"bad request line {line!r}")
            method, target = parts[0], parts[1]
            if method != "GET":
                code, ctype, body = (405, "text/plain; charset=utf-8",
                                     "GET only\n")
            else:
                code, ctype, body = self._route(target.split("?", 1)[0])
            payload = body.encode()
            reason = {200: "OK", 404: "Not Found",
                      405: "Method Not Allowed"}.get(code, "Error")
            head = (
                f"HTTP/1.1 {code} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode("latin-1")
            writer.write(head + payload)
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, ValueError, OSError) as exc:
            self._c_dropped.inc()
            logger.debug("obs request dropped: %r", exc)
        finally:
            # suppress: best-effort close of a possibly-dead diagnostics
            # socket; the request itself was already served or logged
            with contextlib.suppress(Exception):
                writer.close()


def http_get(host: str, port: int, path: str,
             timeout_s: float = 3.0) -> str:
    """Blocking one-shot GET helper (stdlib only) for pollers like
    ``obs.top`` and ``bench.py --net`` — returns the body, raises
    ``OSError``/``ValueError`` on failure or non-200."""
    import urllib.request

    url = f"http://{host}:{port}{path}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        if resp.status != 200:
            raise ValueError(f"{url}: HTTP {resp.status}")
        return resp.read().decode()

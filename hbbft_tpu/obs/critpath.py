"""Per-transaction end-to-end critical path across node/client journals.

The merge/analysis half of the causal tracing layer
(:mod:`hbbft_tpu.obs.trace` is the capture half): read every flight
journal of a run — the nodes' plus any ``ClusterClient(trace_dir=…)``
journals — and answer *where a transaction's latency went*, across
processes, with explicitly-bounded clock uncertainty::

    python -m hbbft_tpu.obs.critpath JOURNAL_DIR... [--json] [--waterfalls N]

**Clock alignment (NTP-style, bound reported — never a point
estimate).**  Each journal's timestamps come from its own process clock.
For every directed pair of processes the matched send/receive pairs —
consensus messages between nodes (paired FIFO per (sender, receiver,
payload digest), like the forensic audit), and the per-tx trace stages
between a client and its node (``submit``→``ingress``, one direction;
``commit``→``commit_seen``, the other) — give one-way delay samples
``t_recv − t_send = delay + θ`` with ``delay > 0``, so the offset
``θ = clock_B − clock_A`` is bounded by the two directions' minima::

    θ ∈ [ −min(B→A samples),  +min(A→B samples) ]

Timestamps are aligned using the interval **midpoint**, and every node's
accumulated interval **width** is reported alongside (``clock_offsets``)
— a decomposition component smaller than the bound is noise, and the
report says so rather than pretending micro-second precision.  Under the
simulator every journal shares the virtual clock, the bounds collapse to
the per-hop cost-model charge, and the whole report is byte-identical
across identical-seed runs.

**Span timebase conversion.**  Runtime span records carry
``perf_counter`` phase times while record stamps are wall clock; the two
are bridged per (node, era, epoch) by the identity
``conv = commit_record.t − epoch_span.t_end`` (both are appended in the
same batch-absorb call, so the pairing error is the append cost, ~µs).

**Decomposition (components sum EXACTLY to the measured total).**  Each
reconstructed tx's milestones are clamped into a monotone chain
``submit → ingress → queued → epoch_start → first_rbc → rbc_end →
aba_end → commit [→ commit_retrieved] → commit_seen`` and consecutive
differences become the components ``wire / pump_queue / mempool_wait /
proposal_wait / rbc / aba / coin / decrypt`` (+ ``retrieve`` for VID
mode's post-ordering payload fetch, + ``other`` for time the journals
could not attribute — counted, never silently spread).  ``coin`` is carved out of
the ABA window (coin spans nest inside ABA rounds); matched inbound
message delays on the committing node are carved out of the rbc/aba/
decrypt windows into ``wire`` — a shaped 100 ms link shows up as wire
time, not as a mysteriously slow protocol phase.

Fault accounting: receives with no matching send, trace stages that
never pair up, and nodes that could not be clock-aligned are all
counted in the report (``unmatched``), never dropped silently.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from bisect import bisect_left, bisect_right
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.obs.flight import (
    FlightCommit,
    FlightMsg,
    FlightSpan,
    Journal,
    find_journal_dirs,
    read_journal,
    target_covers,
)
from hbbft_tpu.obs.spans import phase_group
from hbbft_tpu.obs.trace import FlightTrace, iter_tids

#: decomposition components, in chain order (``other`` = time the
#: journals could not attribute to a phase — missing spans, torn tails;
#: ``retrieve`` = VID mode's post-ordering payload fetch, the gap
#: between the ``commit`` and ``commit_retrieved`` stages)
COMPONENTS = ("wire", "pump_queue", "mempool_wait", "proposal_wait",
              "rbc", "aba", "coin", "decrypt", "retrieve", "other")


def _digest(payload: bytes) -> str:
    return hashlib.sha3_256(payload).hexdigest()[:16]


def _r(x: float) -> float:
    """Output rounding: 9 decimals (ns) keeps identical-seed runs
    byte-identical across platforms' float formatting."""
    return round(float(x), 9)


# ===========================================================================
# Journal extraction
# ===========================================================================


@dataclass
class _NodeData:
    """One node journal's trace-relevant slices."""

    name: str
    flavor: str
    # tid → earliest (t, detail) per stage
    ingress: Dict[bytes, Tuple[float, str]] = field(default_factory=dict)
    queued: Dict[bytes, float] = field(default_factory=dict)
    # tid → (t, era, epoch) of the commit-stage trace on THIS node
    commit: Dict[bytes, Tuple[float, int, int]] = field(
        default_factory=dict)
    # tid → (t, era, epoch) of the commit_retrieved trace (VID mode:
    # when the lazily-fetched payload resolved on THIS node)
    commit_retrieved: Dict[bytes, Tuple[float, int, int]] = field(
        default_factory=dict)
    # (era, epoch) → earliest FlightCommit record t
    commit_rec_t: Dict[Tuple[int, int], float] = field(
        default_factory=dict)
    # (era, epoch) → list of FlightSpan
    spans: Dict[Tuple[int, int], List[FlightSpan]] = field(
        default_factory=dict)
    # messages for offset estimation / wire carve-out
    msgs_in: List[FlightMsg] = field(default_factory=list)
    msgs_out: List[FlightMsg] = field(default_factory=list)


@dataclass
class _ClientData:
    """One client journal's per-tx stages."""

    name: str
    submit: Dict[bytes, float] = field(default_factory=dict)
    ack: Dict[bytes, float] = field(default_factory=dict)
    # tid → (t, era, epoch)
    commit_seen: Dict[bytes, Tuple[float, int, int]] = field(
        default_factory=dict)


def _extract(journals: Sequence[Journal]
             ) -> Tuple[Dict[str, _NodeData], Dict[str, _ClientData]]:
    nodes: Dict[str, _NodeData] = {}
    clients: Dict[str, _ClientData] = {}
    for j in journals:
        if j.flavor == "client":
            c = clients.setdefault(j.node, _ClientData(j.node))
            for _inc, rec in j.records:
                if not isinstance(rec, FlightTrace):
                    continue
                for tid in iter_tids(rec.tids):
                    if rec.stage == "submit":
                        if tid not in c.submit:
                            c.submit[tid] = rec.t
                    elif rec.stage == "ack":
                        if tid not in c.ack:
                            c.ack[tid] = rec.t
                    elif rec.stage == "commit_seen":
                        if tid not in c.commit_seen:
                            c.commit_seen[tid] = (rec.t, rec.era,
                                                  rec.epoch)
            continue
        nd = nodes.setdefault(j.node, _NodeData(j.node, j.flavor))
        for _inc, rec in j.records:
            if isinstance(rec, FlightTrace):
                for tid in iter_tids(rec.tids):
                    if rec.stage == "ingress":
                        if tid not in nd.ingress:
                            nd.ingress[tid] = (rec.t, rec.detail)
                    elif rec.stage == "queued":
                        if tid not in nd.queued:
                            nd.queued[tid] = rec.t
                    elif rec.stage == "commit":
                        if tid not in nd.commit:
                            nd.commit[tid] = (rec.t, rec.era, rec.epoch)
                    elif rec.stage == "commit_retrieved":
                        if tid not in nd.commit_retrieved:
                            nd.commit_retrieved[tid] = (rec.t, rec.era,
                                                        rec.epoch)
            elif isinstance(rec, FlightCommit):
                key = (rec.era, rec.epoch)
                if key not in nd.commit_rec_t:
                    nd.commit_rec_t[key] = rec.t
            elif isinstance(rec, FlightSpan):
                nd.spans.setdefault((rec.era, rec.epoch), []).append(rec)
            elif isinstance(rec, FlightMsg):
                if rec.direction == "in":
                    nd.msgs_in.append(rec)
                else:
                    nd.msgs_out.append(rec)
    return nodes, clients


# ===========================================================================
# Clock offsets: pairwise one-way-delay minima → bounded offsets
# ===========================================================================


@dataclass
class _Alignment:
    #: process name → clock offset vs the anchor (midpoint estimate)
    offset: Dict[str, float]
    #: process name → accumulated offset-interval width along the
    #: alignment path (the BOUND: components below this are noise)
    bound: Dict[str, float]
    anchor: str
    edges: List[Dict[str, Any]]
    unmatched_receives: int
    unaligned: List[str]


def _collect_delay_minima(nodes: Dict[str, _NodeData],
                          clients: Dict[str, _ClientData],
                          ) -> Tuple[Dict[Tuple[str, str], Tuple[float,
                                                                 int]],
                                     int,
                                     Dict[str, List[Tuple[float, float]]]]:
    """min one-way delay sample per directed (sender, receiver) pair,
    the unmatched-receive count, and per-receiver matched (t_recv,
    delay_sample) lists for the wire carve-out (delay samples still
    carry the pair's clock offset here; the carve-out corrects them
    once offsets are known)."""
    # FIFO pairing per (sender, receiver, payload digest), like the audit
    outs: Dict[Tuple[str, str, str], List[float]] = defaultdict(list)
    ins: Dict[Tuple[str, str, str], List[float]] = defaultdict(list)
    node_names = sorted(nodes)
    for name in node_names:
        nd = nodes[name]
        for rec in nd.msgs_out:
            if not rec.payload:
                continue
            d = _digest(rec.payload)
            for other in node_names:
                if other != name and target_covers(rec.peer, other):
                    outs[(name, other, d)].append(rec.t)
        for rec in nd.msgs_in:
            if not rec.payload:
                continue
            ins[(rec.peer, name, _digest(rec.payload))].append(rec.t)
    minima: Dict[Tuple[str, str], Tuple[float, int]] = {}
    recv_delays: Dict[str, List[Tuple[float, float]]] = defaultdict(list)
    unmatched = 0

    def feed(a: str, b: str, sample: float, t_recv: float) -> None:
        cur = minima.get((a, b))
        minima[(a, b)] = (sample if cur is None else min(cur[0], sample),
                          1 if cur is None else cur[1] + 1)
        recv_delays[b].append((t_recv, sample))

    for key in sorted(ins):
        sender, receiver, _d = key
        in_ts = sorted(ins[key])
        out_ts = sorted(outs.get(key, ()))
        k = min(len(in_ts), len(out_ts))
        for i in range(k):
            feed(sender, receiver, in_ts[i] - out_ts[i], in_ts[i])
        unmatched += len(in_ts) - k
    # client↔node edges from the per-tx trace stages
    for cname in sorted(clients):
        c = clients[cname]
        for name in node_names:
            nd = nodes[name]
            for tid in sorted(c.submit):
                hit = nd.ingress.get(tid)
                if hit is not None:
                    feed(cname, name, hit[0] - c.submit[tid], hit[0])
            for tid in sorted(c.commit_seen):
                hit = nd.commit.get(tid)
                if hit is not None:
                    t_seen = c.commit_seen[tid][0]
                    feed(name, cname, t_seen - hit[0], t_seen)
    return minima, unmatched, recv_delays


def _align(nodes: Dict[str, _NodeData],
           clients: Dict[str, _ClientData],
           ) -> Tuple[_Alignment, Dict[str, List[Tuple[float, float]]]]:
    minima, unmatched, recv_delays = _collect_delay_minima(nodes, clients)
    names = sorted(nodes) + sorted(clients)
    # undirected edges where BOTH directions produced samples: the
    # offset interval is [-min_ba, +min_ab]
    edges: Dict[Tuple[str, str], Tuple[float, float, int]] = {}
    for (a, b), (d_ab, n_ab) in sorted(minima.items()):
        if a > b:
            continue
        back = minima.get((b, a))
        if back is None:
            continue
        d_ba, n_ba = back
        # θ = clock_b − clock_a ∈ [−d_ba, +d_ab]
        edges[(a, b)] = ((d_ab - d_ba) / 2.0, d_ab + d_ba, n_ab + n_ba)
    anchor = sorted(nodes)[0] if nodes else (names[0] if names else "")
    offset: Dict[str, float] = {anchor: 0.0} if anchor else {}
    bound: Dict[str, float] = {anchor: 0.0} if anchor else {}
    # BFS from the anchor over bounded edges, accumulating widths;
    # visit order is sorted for determinism
    frontier = [anchor] if anchor else []
    while frontier:
        nxt: List[str] = []
        for cur in frontier:
            for (a, b), (mid, width, _n) in sorted(edges.items()):
                if a == cur and b not in offset:
                    offset[b] = offset[a] + mid
                    bound[b] = bound[a] + width
                    nxt.append(b)
                elif b == cur and a not in offset:
                    offset[a] = offset[b] - mid
                    bound[a] = bound[b] + width
                    nxt.append(a)
        frontier = sorted(nxt)
    unaligned = [n for n in names if n not in offset]
    for n in unaligned:
        # counted above; aligning at 0 keeps the tx chain monotone-
        # clampable instead of discarding every tx touching the process
        offset[n] = 0.0
        bound[n] = float("inf")
    edge_docs = [
        {"a": a, "b": b, "offset_s": _r(mid), "bound_s": _r(width),
         "samples": n}
        for (a, b), (mid, width, n) in sorted(edges.items())
    ]
    align = _Alignment(offset=offset, bound=bound, anchor=anchor,
                       edges=edge_docs, unmatched_receives=unmatched,
                       unaligned=unaligned)
    # wire carve-out index: matched inbound (t_recv, delay) per node,
    # aligned to the anchor clock and offset-corrected, sorted by time
    carve: Dict[str, List[Tuple[float, float]]] = {}
    for name, samples in sorted(recv_delays.items()):
        if name not in nodes:
            continue
        off = offset[name]
        fixed = []
        for t_recv, raw in samples:
            # raw = true_delay + θ(sender→receiver path); correcting by
            # the estimated pairwise offset leaves delay ± the bound
            fixed.append((t_recv - off, max(0.0, raw)))
        fixed.sort()
        carve[name] = fixed
    return align, carve


# ===========================================================================
# Per-epoch phase windows (span timebase converted, clock aligned)
# ===========================================================================


@dataclass
class _EpochPhases:
    epoch_start: float
    first_rbc: float
    rbc_end: float
    aba_end: float
    decrypt_end: float
    coin_s: float


def _epoch_phases(nd: _NodeData, key: Tuple[int, int],
                  node_offset: float) -> Optional[_EpochPhases]:
    spans = nd.spans.get(key)
    commit_t = nd.commit_rec_t.get(key)
    if not spans or commit_t is None:
        return None
    epoch_span = next((s for s in spans if s.name == "epoch"), None)
    if epoch_span is None:
        return None
    # span clock → record clock: both the epoch span and the commit
    # record are appended in the same batch-absorb call
    conv = (commit_t - epoch_span.t_end) - node_offset
    by_group: Dict[str, List[FlightSpan]] = defaultdict(list)
    for s in spans:
        by_group[phase_group(s.name)].append(s)
    t0 = epoch_span.t_start + conv
    rbc = by_group.get("rbc", ())
    aba = by_group.get("aba", ())
    coin = by_group.get("coin", ())
    dec = by_group.get("decrypt", ())
    first_rbc = (min(s.t_start for s in rbc) + conv) if rbc else t0
    rbc_end = (max(s.t_end for s in rbc) + conv) if rbc else first_rbc
    aba_like = list(aba) + list(coin)
    aba_end = (max(s.t_end for s in aba_like) + conv) if aba_like \
        else rbc_end
    decrypt_end = (max(s.t_end for s in dec) + conv) if dec else aba_end
    coin_s = sum(s.t_end - s.t_start for s in coin)
    return _EpochPhases(epoch_start=t0, first_rbc=first_rbc,
                        rbc_end=rbc_end, aba_end=aba_end,
                        decrypt_end=decrypt_end, coin_s=coin_s)


def _wire_in_window(carve: List[Tuple[float, float]], a: float,
                    b: float) -> float:
    """Max matched inbound one-way delay arriving in [a, b), capped at
    the window length — the wire share of a phase window (the phase was
    waiting on that arrival; anything beyond the window length belongs
    to an earlier window)."""
    if b <= a or not carve:
        return 0.0
    lo = bisect_left(carve, (a, -1.0))
    hi = bisect_right(carve, (b, -1.0))
    best = 0.0
    for i in range(lo, hi):
        if carve[i][1] > best:
            best = carve[i][1]
    return min(best, b - a)


# ===========================================================================
# Per-tx assembly
# ===========================================================================


def _assemble(nodes: Dict[str, _NodeData],
              clients: Dict[str, _ClientData],
              align: _Alignment,
              carve: Dict[str, List[Tuple[float, float]]],
              ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """One waterfall dict per reconstructable tx + the miss counters."""
    off = align.offset
    misses = {"no_ingress": 0, "no_queued": 0, "no_commit": 0,
              "no_commit_seen": 0}
    # which client submitted each tid (earliest submit wins)
    submitter: Dict[bytes, str] = {}
    for cname in sorted(clients):
        for tid, t in clients[cname].submit.items():
            cur = submitter.get(tid)
            if cur is None or t - off[cname] < (
                    clients[cur].submit[tid] - off[cur]):
                submitter[tid] = cname
    # every committed tid, from every node's commit-stage traces
    committed: Dict[bytes, Tuple[str, float, int, int]] = {}
    for name in sorted(nodes):
        for tid, (t, era, epoch) in nodes[name].commit.items():
            t_al = t - off[name]
            cur = committed.get(tid)
            if cur is None or t_al < cur[1]:
                committed[tid] = (name, t_al, era, epoch)
    phase_cache: Dict[Tuple[str, int, int], Optional[_EpochPhases]] = {}
    rows: List[Dict[str, Any]] = []
    for tid in sorted(committed):
        # the tx's home node: where it ingressed (falls back to the
        # earliest committer for foreign/unseen ingress)
        home = None
        for name in sorted(nodes):
            if tid in nodes[name].ingress:
                home = name
                break
        if home is None:
            misses["no_ingress"] += 1
            continue
        nd = nodes[home]
        h_off = off[home]
        t_ingress = nd.ingress[tid][0] - h_off
        commit_here = nd.commit.get(tid)
        if commit_here is None:
            misses["no_commit"] += 1
            continue
        t_commit = commit_here[0] - h_off
        era, epoch = commit_here[1], commit_here[2]
        # VID mode: when the lazily-retrieved payload became readable on
        # the home node (== t_commit for locally-dispersed payloads,
        # absent entirely in classic-RBC mode)
        retrieved_here = nd.commit_retrieved.get(tid)
        t_retrieved = (retrieved_here[0] - h_off
                       if retrieved_here is not None else None)
        t_queued = nd.queued.get(tid)
        if t_queued is not None:
            t_queued -= h_off
        cname = submitter.get(tid)
        t_submit = t_ack = t_seen = None
        if cname is not None:
            c = clients[cname]
            t_submit = c.submit[tid] - off[cname]
            if tid in c.ack:
                t_ack = c.ack[tid] - off[cname]
            seen = c.commit_seen.get(tid)
            if seen is not None:
                t_seen = seen[0] - off[cname]
            else:
                misses["no_commit_seen"] += 1
        ckey = (home, era, epoch)
        ph = phase_cache.get(ckey)
        if ckey not in phase_cache:
            ph = _epoch_phases(nd, (era, epoch), h_off)
            phase_cache[ckey] = ph
        comp = {k: 0.0 for k in COMPONENTS}
        start = t_submit if t_submit is not None else t_ingress
        cur = start

        def take(name: str, t: Optional[float]) -> None:
            nonlocal cur
            if t is None:
                return
            t = max(t, cur)
            comp[name] += t - cur
            cur = t

        take("wire", t_ingress)
        if t_queued is None and nd.flavor == "runtime":
            misses["no_queued"] += 1
        take("pump_queue", t_queued)
        if ph is not None:
            seg0 = cur
            take("mempool_wait", ph.epoch_start)
            take("proposal_wait", ph.first_rbc)
            rbc_a = cur
            take("rbc", ph.rbc_end)
            aba_a = cur
            take("aba", ph.aba_end)
            dec_a = cur
            take("decrypt", max(ph.decrypt_end, t_commit))
            take("other", t_commit)
            # coin is a carve-out of the ABA window (coin spans nest
            # inside ABA rounds)
            coin = min(comp["aba"], ph.coin_s)
            comp["aba"] -= coin
            comp["coin"] += coin
            # wire carve-out: matched inbound delays landing inside a
            # phase window were network wait, not protocol work —
            # a shaped link must surface as wire time
            cv = carve.get(home, [])
            for g, (a, b) in (("rbc", (rbc_a, aba_a)),
                              ("aba", (aba_a, dec_a)),
                              ("decrypt", (dec_a, cur))):
                w = min(_wire_in_window(cv, a, b), comp[g])
                comp[g] -= w
                comp["wire"] += w
            del seg0
        else:
            take("other", t_commit)
        # post-ordering retrieval (VID): commit → commit_retrieved — by
        # construction the pre-retrieve components sum exactly to
        # submit→commit, and adding ``retrieve`` extends the identity to
        # submit→commit_retrieved
        take("retrieve", t_retrieved)
        take("wire", t_seen)
        total = cur - start
        row = {
            "tid": tid.hex(),
            "node": home,
            "client": cname,
            "era": era,
            "epoch": epoch,
            "t_submit": _r(t_submit) if t_submit is not None else None,
            "t_ingress": _r(t_ingress),
            "t_commit": _r(t_commit),
            "t_commit_retrieved": (_r(t_retrieved)
                                   if t_retrieved is not None else None),
            "t_commit_seen": _r(t_seen) if t_seen is not None else None,
            "t_ack": _r(t_ack) if t_ack is not None else None,
            "total_s": _r(total),
            "components": {k: _r(v) for k, v in comp.items()},
        }
        rows.append(row)
    return rows, misses


# ===========================================================================
# Aggregation + report
# ===========================================================================


def _percentile_row(rows: List[Dict[str, Any]], q: float
                    ) -> Dict[str, Any]:
    """Nearest-rank percentile BY TOTAL, reporting that tx's own
    decomposition — so the components sum to exactly the percentile
    latency shown (an average of decompositions would not)."""
    ordered = sorted(rows, key=lambda r: (r["total_s"], r["tid"]))
    idx = max(0, min(len(ordered) - 1,
                     int(round(q / 100.0 * len(ordered) + 0.5)) - 1))
    row = ordered[idx]
    comps = row["components"]
    dominant = max(sorted(comps), key=lambda k: comps[k])
    return {
        "total_s": row["total_s"],
        "components": comps,
        "dominant": dominant,
        "dominant_s": comps[dominant],
        "tid": row["tid"],
        "node": row["node"],
    }


def build_report(paths: Sequence[str], waterfalls: int = 5
                 ) -> Dict[str, Any]:
    """The full critical-path report over one run's journal dirs."""
    journals = [read_journal(d) for d in paths]
    nodes, clients = _extract(journals)
    align, carve = _align(nodes, clients)
    rows, misses = _assemble(nodes, clients, align, carve)
    committed_tids = set()
    for nd in nodes.values():
        committed_tids.update(nd.commit)
    n_committed = len(committed_tids)
    mean = {k: 0.0 for k in COMPONENTS}
    for row in rows:
        for k in COMPONENTS:
            mean[k] += row["components"][k]
    if rows:
        mean = {k: _r(v / len(rows)) for k, v in mean.items()}
    report: Dict[str, Any] = {
        "journals": len(journals),
        "nodes": sorted(nodes),
        "clients": sorted(clients),
        "anchor": align.anchor,
        "clock_offsets": {
            n: {"offset_s": _r(align.offset[n]),
                "bound_s": (_r(align.bound[n])
                            if align.bound[n] != float("inf") else None)}
            for n in sorted(align.offset)
        },
        "clock_edges": align.edges,
        "txs_committed": n_committed,
        "txs_reconstructed": len(rows),
        "reconstructed_fraction": (
            _r(len(rows) / n_committed) if n_committed else 0.0),
        "unmatched": dict(sorted(misses.items()), **{
            "receives": align.unmatched_receives,
            "unaligned_processes": align.unaligned,
        }),
        "mean_components": mean,
    }
    if rows:
        report["p50"] = _percentile_row(rows, 50.0)
        report["p99"] = _percentile_row(rows, 99.0)
    # waterfalls: the slowest txs first — where the long tail lives
    slowest = sorted(rows, key=lambda r: (-r["total_s"], r["tid"]))
    report["waterfalls"] = slowest[:max(0, waterfalls)]
    return report


def render(report: Dict[str, Any]) -> str:
    """Human-readable report (the default CLI output)."""
    lines = [
        f"critpath: {report['journals']} journals — "
        f"{len(report['nodes'])} nodes, {len(report['clients'])} clients",
        f"txs committed={report['txs_committed']} "
        f"reconstructed={report['txs_reconstructed']} "
        f"({report['reconstructed_fraction'] * 100:.1f}%)",
    ]
    for n in report["nodes"] + report["clients"]:
        doc = report["clock_offsets"].get(n, {})
        b = doc.get("bound_s")
        lines.append(
            f"  clock {n}: offset {doc.get('offset_s', 0.0) * 1e3:.3f} ms"
            + (f" ± {b * 1e3 / 2:.3f} ms" if b is not None
               else " (UNALIGNED)"))
    for p in ("p50", "p99"):
        doc = report.get(p)
        if doc is None:
            continue
        comps = " ".join(
            f"{k}={doc['components'][k] * 1e3:.2f}ms"
            for k in COMPONENTS if doc["components"][k] > 0)
        lines.append(f"{p}: {doc['total_s'] * 1e3:.2f} ms "
                     f"[dominant: {doc['dominant']} "
                     f"{doc['dominant_s'] * 1e3:.2f} ms] {comps}")
    um = report["unmatched"]
    lines.append(
        "unmatched: " + " ".join(f"{k}={um[k]}" for k in sorted(um)
                                 if k != "unaligned_processes"))
    for row in report["waterfalls"]:
        comps = " ".join(
            f"{k}={row['components'][k] * 1e3:.2f}"
            for k in COMPONENTS if row["components"][k] > 0)
        lines.append(
            f"  tx {row['tid'][:8]} ({row['node']} e{row['era']}/"
            f"{row['epoch']}): {row['total_s'] * 1e3:.2f} ms  {comps}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.critpath",
        description="per-transaction end-to-end critical path across "
                    "node/client flight journals")
    ap.add_argument("paths", nargs="+",
                    help="journal dirs (or roots holding node-N/ dirs)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as deterministic JSON")
    ap.add_argument("--waterfalls", type=int, default=5,
                    help="per-tx waterfalls to include (slowest first)")
    args = ap.parse_args(argv)
    dirs: List[str] = []
    for p in args.paths:
        dirs.extend(find_journal_dirs(p))
    if not dirs:
        print(f"no journal segments under {args.paths!r}",
              file=sys.stderr)
        return 2
    report = build_report(sorted(dirs), waterfalls=args.waterfalls)
    if args.json:
        print(json.dumps(report, sort_keys=True, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via -m
    sys.exit(main())

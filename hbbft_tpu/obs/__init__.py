"""Unified observability: metrics registry, epoch-phase spans, exposition.

The layer the ROADMAP's "production-scale, heavy traffic" north star needs
before any further perf PR can be honestly measured: "The Latency Price of
Threshold Cryptosystems" (PAPERS.md) shows that phase attribution — where
inside an epoch the latency goes (RBC echo fan-out? ABA coin flips? TPKE
decrypt-share combine?) — dominates threshold-crypto BFT analysis, and
Thetacrypt treats a built-in metrics service as table stakes.

- :mod:`hbbft_tpu.obs.metrics` — dependency-free labeled
  Counter/Gauge/Histogram registry with Prometheus-text and JSON exposition
  (naming convention ``hbbft_<layer>_<name>``, enforced by
  ``tools_check_metrics.py`` in tier 1);
- :mod:`hbbft_tpu.obs.spans` — the epoch-phase tracer protocols report into
  via the :class:`hbbft_tpu.traits.StepObserver` hook: per-epoch spans for
  RBC Value/Echo/Ready, per-ABA-round BVal/Aux/Conf + coin, threshold-decrypt
  share/combine, and DKG rotation, exportable as JSONL;
- :mod:`hbbft_tpu.obs.http` — the asyncio ``/metrics``, ``/status``,
  ``/spans``, ``/flight`` endpoint every
  :class:`~hbbft_tpu.net.runtime.NodeRuntime` serves;
- :mod:`hbbft_tpu.obs.top` — ``python -m hbbft_tpu.obs.top``, a curses-free
  live cluster view polling all nodes;
- :mod:`hbbft_tpu.obs.flight` — the black-box flight recorder: a bounded
  segment-rotated on-disk journal of protocol events (messages, commits
  with the ledger-digest chain, faults, spans, lifecycle notes), identical
  format from both ``VirtualNet`` and ``NodeRuntime``;
- :mod:`hbbft_tpu.obs.audit` — ``python -m hbbft_tpu.obs.audit``, the
  cross-node forensic auditor: merged causal timeline, digest-chain
  agreement, first-divergent-epoch fork reports, equivocation evidence
  keyed to ``FaultKind``.
"""

from hbbft_tpu.obs.flight import FlightObserver, FlightRecorder
from hbbft_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    histogram_quantile,
    parse_prometheus_text,
)
from hbbft_tpu.obs.spans import Span, SpanTracer

__all__ = [
    "Counter",
    "FlightObserver",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "SpanTracer",
    "histogram_quantile",
    "parse_prometheus_text",
]

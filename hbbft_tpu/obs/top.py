"""``python -m hbbft_tpu.obs.top`` — curses-free live cluster view.

Polls every node's obs endpoint (``/status`` + ``/metrics``), and renders a
refreshing plain-ANSI table: per-node era/epoch/batches, live epoch rate
(batches delta over the poll interval), mempool depth, connected peers,
fault and decode counters, the performance plane's ``util%`` (worst
per-layer utilization, i.e. ``100·(1 − headroom)``) and the
bidirectional controller's ``ctrl`` state (``+N`` degraded N levels,
``-N`` raised N boosts, ``0`` at exact bases) — plus the
cluster-aggregated per-phase p50/p99 (from the
``hbbft_phase_duration_seconds`` histograms, buckets summed across
nodes), which is the "where does the epoch latency go" line.

    python -m hbbft_tpu.obs.top --targets 127.0.0.1:26000,127.0.0.1:26001
    python -m hbbft_tpu.obs.top --base-port 26000 --nodes 4

``--gateways host:port,…`` additionally polls client-gateway obs
endpoints (a gateway started with ``--metrics-port`` serves the same
``/status`` + ``/metrics`` shape) and renders a second table — clients,
pending pool, forward queue, live node links, forwarded/relayed/shed
totals — so the ingest tier shows up next to the nodes it feeds.

``--iterations N`` renders N frames then exits (``1`` = one plain snapshot,
used by scripts/tests); the default runs until interrupted.  ``--json``
polls ONCE and emits the whole snapshot — per-node status, mesh-collective
and loadgen (``hbbft_load_*``) totals, gateway tier, cluster phase
quantiles — as one JSON document for scripts to consume.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional, Tuple

from hbbft_tpu.obs.http import http_get
from hbbft_tpu.obs.metrics import histogram_quantile, parse_prometheus_text

Target = Tuple[str, int]

#: phase rows shown in the breakdown, in protocol order
TOP_PHASES = (
    "rbc_value", "rbc_echo", "rbc_ready", "aba_bval", "aba_aux",
    "aba_conf", "aba_coin", "decrypt_share", "decrypt_combine",
    "dkg_rotation",
)


def poll_target(host: str, port: int, timeout_s: float = 2.0
                ) -> Optional[dict]:
    """One node's ``{"status":…, "metrics":…, "health":…}`` snapshot,
    None if down.  ``health`` is None (not a failure) on endpoints that
    predate the ``/health`` route or serve an empty document."""
    try:
        status = http_get(host, port, "/status", timeout_s)
        metrics = http_get(host, port, "/metrics", timeout_s)
    # hblint: disable=fault-swallowed-drop (poller client side: a down
    # node renders as the DOWN row — that IS the accounting)
    except (OSError, ValueError):
        return None
    import json

    health = None
    try:
        health = json.loads(http_get(host, port, "/health", timeout_s))
    # hblint: disable=fault-swallowed-drop (benign: /health is optional
    # — old endpoints and gateways render a "-" health cell, nothing
    # is dropped)
    except (OSError, ValueError):
        health = None
    try:
        return {
            "status": json.loads(status),
            "metrics": parse_prometheus_text(metrics),
            "health": health or None,
        }
    # hblint: disable=fault-swallowed-drop (same: unparseable responses
    # render the node as DOWN)
    except ValueError:
        return None


def metric_total(snap: dict, name: str) -> Optional[float]:
    """Sum of one counter family across its label sets, None if the
    node doesn't export it (e.g. ``hbbft_load_*`` without an embedded
    load generator)."""
    series = snap["metrics"].get(name)
    if not series:
        return None
    return sum(v for _labels, v in series)


def phase_quantiles(snaps: List[Optional[dict]],
                    qs=(0.5, 0.99)) -> Dict[str, List[float]]:
    """Cluster-wide per-phase quantiles: histogram buckets summed over
    nodes, then interpolated."""
    acc: Dict[str, Dict[float, float]] = {}
    for snap in snaps:
        if snap is None:
            continue
        series = snap["metrics"].get(
            "hbbft_phase_duration_seconds_bucket", []
        )
        for labels, value in series:
            phase = labels.get("phase", "?")
            le = float("inf") if labels.get("le") == "+Inf" else float(
                labels.get("le", "inf")
            )
            by_le = acc.setdefault(phase, {})
            by_le[le] = by_le.get(le, 0.0) + value
    out: Dict[str, List[float]] = {}
    for phase, by_le in acc.items():
        cum = sorted(by_le.items())
        out[phase] = [histogram_quantile(cum, q) for q in qs]
    return out


def util_cell(status: dict) -> Tuple[str, Optional[float]]:
    """(table cell, percent) for the perf plane's utilization: the
    worst per-layer busy fraction as ``100·(1 − headroom)`` — "-" until
    the node's sampler has completed its first window."""
    headroom = (status.get("perf") or {}).get("headroom")
    if headroom is None:
        headroom = status.get("headroom")
    if headroom is None:
        return "-", None
    pct = max(0.0, min(100.0, (1.0 - float(headroom)) * 100.0))
    return f"{pct:.0f}", pct


def ctrl_summary(status: dict) -> Tuple[str, Optional[dict]]:
    """(table cell, JSON doc) for the bidirectional controller:
    effective level (``+N`` = degraded N levels, ``-N`` = raised N
    boosts, ``0`` = exact bases) plus current/base proposer batch
    size.  "-" on nodes without a controller."""
    dg = status.get("degraded") or {}
    if "level" not in dg:
        return "-", None
    level = int(dg.get("level") or 0)
    boost = int(dg.get("boost") or 0)
    effective = level - boost
    doc = {
        "level": level,
        "boost": boost,
        "effective": effective,
        "batch_size": dg.get("batch_size"),
        "base_batch_size": dg.get("base_batch_size"),
    }
    return (f"{effective:+d}" if effective else "0"), doc


def render_gateways(gw_targets: List[Target],
                    gw_cur: List[Optional[dict]]) -> List[str]:
    """The gateway-tier table (empty list when no gateways polled)."""
    if not gw_targets:
        return []
    lines = [
        "",
        f"{'gateway':<22} {'id':>4} {'clients':>7} {'pending':>7} "
        f"{'fwdq':>5} {'links':>5} {'fwd':>8} {'commits':>8} "
        f"{'sheds':>6} {'failover':>8} {'drops':>6}",
    ]
    for i, (host, port) in enumerate(gw_targets):
        snap = gw_cur[i]
        name = f"{host}:{port}"
        if snap is None:
            lines.append(f"{name:<22} DOWN")
            continue
        d = snap["status"]
        links = d.get("links") or []
        live = sum(1 for li in links if li.get("connected"))
        drops = metric_total(snap, "hbbft_gw_client_drops_total")
        lines.append(
            f"{name:<22} {d.get('gateway', '?'):>4} "
            f"{d.get('clients', 0):>7} {d.get('pending', 0):>7} "
            f"{d.get('forward_queue', 0):>5} "
            f"{f'{live}/{len(links)}':>5} {d.get('forwarded', 0):>8} "
            f"{d.get('commits_relayed', 0):>8} {d.get('sheds', 0):>6} "
            f"{d.get('link_failovers', 0):>8} "
            f"{'-' if drops is None else int(drops):>6}"
        )
    return lines


def render(targets: List[Target], prev: List[Optional[dict]],
           cur: List[Optional[dict]], dt: float,
           gw_targets: List[Target] = (),
           gw_cur: List[Optional[dict]] = ()) -> str:
    lines: List[str] = []
    lines.append(
        f"hbbft-tpu obs.top — {len(targets)} nodes — "
        f"{time.strftime('%H:%M:%S')}  (poll {dt:.1f}s)"
    )
    lines.append(
        f"{'node':<22} {'era':>4} {'epoch':>6} {'batch':>6} "
        f"{'ep/s':>6} {'mempool':>8} {'peers':>5} {'txs':>8} "
        f"{'faults':>6} {'decode!':>7} {'gaps':>5} {'guard!':>6} "
        f"{'degr':>4} {'util%':>5} {'ctrl':>4} {'vidp':>5} "
        f"{'health':>8} "
        f"{'jrnl':>7} {'jseg':>4} {'jwf':>4} {'mesh':>6} "
        f"{'load':>8} {'shed':>5}"
    )
    for i, (host, port) in enumerate(targets):
        snap = cur[i]
        name = f"{host}:{port}"
        if snap is None:
            lines.append(f"{name:<22} DOWN")
            continue
        d = snap["status"]
        rate = ""
        if prev[i] is not None and dt > 0:
            rate = "%.2f" % (
                (d["batches"] - prev[i]["status"]["batches"]) / dt
            )
        # journal health: flight-recorder records/segments/write-failures
        # (the black box an operator audits after an incident — a nonzero
        # jwf means the journal is losing events to disk errors)
        fl = d.get("flight") or {}
        jrnl = fl.get("records", "-")
        jseg = fl.get("segments", "-")
        jwf = fl.get("write_failures", "-")
        # overload-defense engagements: throttles + disconnects +
        # backlog evictions + mempool sheds — nonzero means some peer
        # or client is being actively budgeted (see /status "guard")
        gd = d.get("guard") or {}
        gi = gd.get("ingress") or {}
        guard = (gi.get("throttles", 0) + gi.get("disconnects", 0)
                 + gd.get("senderq_evictions", 0)
                 + sum((gd.get("mempool_sheds") or {}).values()))
        # adaptive-degradation level, lazy-retrieval backlog, and the
        # node's own /health verdict — the live-health-plane columns
        degr = (d.get("degraded") or {}).get("level", "-")
        # perf-plane utilization and bidirectional-controller columns:
        # util% is the worst layer's busy fraction, ctrl the signed
        # effective level (+degrade / -raise / 0 at bases)
        util, _ = util_cell(d)
        ctrl, _ = ctrl_summary(d)
        vidp = (d.get("vid") or {}).get("pending_retrievals", "-")
        health = (snap.get("health") or {}).get("status", "-")
        # mesh-sharded epoch collectives (zero on single-device nodes)
        # and embedded-loadgen counters ("-" when no generator runs in
        # this process — hbbft_load_* lives in whichever registry hosts
        # the OpenLoopGenerator)
        mesh = metric_total(snap, "hbbft_mesh_collectives_total")
        load = metric_total(snap, "hbbft_load_submitted_txs_total")
        shed = metric_total(snap, "hbbft_load_shed_txs_total")

        def _i(v: Optional[float]) -> str:
            return "-" if v is None else str(int(v))

        lines.append(
            f"{name:<22} {d['era']:>4} {d['epoch']:>6} "
            f"{d['batches']:>6} {rate:>6} {d['mempool']:>8} "
            f"{d['peers_connected']:>5} {d['committed_txs']:>8} "
            f"{d['faults_observed']:>6} {d['decode_failures']:>7} "
            f"{d['replay_gaps']:>5} {guard:>6} "
            f"{degr:>4} {util:>5} {ctrl:>4} {vidp:>5} {health:>8} "
            f"{jrnl:>7} {jseg:>4} {jwf:>4} {_i(mesh):>6} "
            f"{_i(load):>8} {_i(shed):>5}"
        )
    lines.extend(render_gateways(list(gw_targets), list(gw_cur)))
    pq = phase_quantiles(cur)
    lines.append("")
    lines.append(f"{'phase':<18} {'p50 ms':>9} {'p99 ms':>9}")
    for phase in TOP_PHASES:
        if phase not in pq:
            continue
        p50, p99 = pq[phase]
        lines.append(f"{phase:<18} {p50 * 1e3:>9.2f} {p99 * 1e3:>9.2f}")
    if not pq:
        lines.append("(no finished epochs yet)")
    return "\n".join(lines)


def snapshot_doc(targets: List[Target],
                 cur: List[Optional[dict]],
                 gw_targets: List[Target] = (),
                 gw_cur: List[Optional[dict]] = ()) -> dict:
    """One-shot machine-readable snapshot (``--json``)."""
    nodes = []
    for i, (host, port) in enumerate(targets):
        snap = cur[i]
        if snap is None:
            nodes.append({"target": f"{host}:{port}", "up": False})
            continue
        d = snap["status"]
        gd = d.get("guard") or {}
        gi = gd.get("ingress") or {}
        hd = snap.get("health") or {}
        nodes.append({
            "target": f"{host}:{port}",
            "up": True,
            "status": snap["status"],
            # the explicit live-health-plane fields, same numbers the
            # text view renders (guard!, degr, vidp, health columns) —
            # scripts must not have to re-derive them from "status"
            "guard": {
                "throttles": gi.get("throttles", 0),
                "disconnects": gi.get("disconnects", 0),
                "senderq_evictions": gd.get("senderq_evictions", 0),
                "mempool_sheds": sum(
                    (gd.get("mempool_sheds") or {}).values()),
            },
            "degrade": d.get("degraded"),
            # the performance-plane / controller fields the text view
            # renders as util% and ctrl
            "perf": d.get("perf"),
            "util_pct": util_cell(d)[1],
            "ctrl": ctrl_summary(d)[1],
            "vid": d.get("vid"),
            "health": hd.get("status"),
            "headroom": hd.get("headroom"),
            "mesh_collectives": metric_total(
                snap, "hbbft_mesh_collectives_total"),
            "mesh_gather_bytes": metric_total(
                snap, "hbbft_mesh_gather_bytes_total"),
            "load": {
                k: metric_total(snap, f"hbbft_load_{k}_total")
                for k in ("offered_txs", "submitted_txs", "acks",
                          "shed_txs", "committed_txs")
            },
        })
    gateways = []
    for i, (host, port) in enumerate(gw_targets):
        snap = gw_cur[i]
        if snap is None:
            gateways.append({"target": f"{host}:{port}", "up": False})
            continue
        drops = metric_total(snap, "hbbft_gw_client_drops_total")
        s = snap["status"]
        links = s.get("links") or []
        gateways.append({
            "target": f"{host}:{port}",
            "up": True,
            "status": snap["status"],
            "client_drops": None if drops is None else int(drops),
            # the explicit gateway-tier fields the text table renders
            "clients": s.get("clients", 0),
            "pending": s.get("pending", 0),
            "forward_queue": s.get("forward_queue", 0),
            "links_up": sum(1 for li in links if li.get("connected")),
            "links": len(links),
            "sheds": s.get("sheds", 0),
            "link_failovers": s.get("link_failovers", 0),
            "health": (snap.get("health") or {}).get("status"),
        })
    pq = phase_quantiles(cur)
    doc = {
        "nodes": nodes,
        "phase_quantiles_ms": {
            ph: {"p50": v[0] * 1e3, "p99": v[1] * 1e3}
            for ph, v in sorted(pq.items())
        },
    }
    if gateways:
        doc["gateways"] = gateways
    return doc


def parse_targets(args) -> List[Target]:
    if args.targets:
        out = []
        for part in args.targets.split(","):
            host, _, port = part.strip().rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
        return out
    if args.base_port:
        return [("127.0.0.1", args.base_port + i)
                for i in range(args.nodes)]
    raise SystemExit("need --targets or --base-port/--nodes")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--targets", default="",
                    help="comma-separated host:port obs endpoints")
    ap.add_argument("--base-port", type=int, default=0,
                    help="metrics base port (node i at base+i)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--gateways", default="",
                    help="comma-separated host:port gateway obs "
                         "endpoints (gateway --metrics-port)")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--iterations", type=int, default=0,
                    help="0 = run until interrupted; 1 = one snapshot")
    ap.add_argument("--json", action="store_true",
                    help="poll once, print a JSON snapshot, exit")
    args = ap.parse_args(argv)
    targets = parse_targets(args)
    gw_targets: List[Target] = []
    for part in args.gateways.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        gw_targets.append((host or "127.0.0.1", int(port)))

    if args.json:
        import json

        cur = [poll_target(h, p) for h, p in targets]
        gw_cur = [poll_target(h, p) for h, p in gw_targets]
        print(json.dumps(
            snapshot_doc(targets, cur, gw_targets, gw_cur),
            sort_keys=True))
        return 0 if any(s is not None for s in cur) else 1

    clear = (sys.stdout.isatty() and args.iterations != 1)
    prev: List[Optional[dict]] = [None] * len(targets)
    t_prev = time.monotonic()
    i = 0
    try:
        while True:
            cur = [poll_target(h, p) for h, p in targets]
            gw_cur = [poll_target(h, p) for h, p in gw_targets]
            now = time.monotonic()
            frame = render(targets, prev, cur, now - t_prev,
                           gw_targets, gw_cur)
            if clear:
                sys.stdout.write("\x1b[H\x1b[2J")
            print(frame, flush=True)
            prev, t_prev = cur, now
            i += 1
            if args.iterations and i >= args.iterations:
                break
            time.sleep(args.interval)
    # hblint: disable=fault-swallowed-drop (interactive exit, not a
    # dropped input: ^C ends the watch loop cleanly)
    except KeyboardInterrupt:
        pass
    return 0 if any(s is not None for s in prev) else 1


if __name__ == "__main__":
    sys.exit(main())

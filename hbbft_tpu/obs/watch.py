"""``python -m hbbft_tpu.obs.watch`` — the anomaly watchtower.

The live half of the health plane: one watchtower process polls every
node and gateway obs endpoint (``/status`` + ``/metrics`` + ``/health``)
*and* tails the cluster's flight journals through the streaming auditor
(:mod:`hbbft_tpu.obs.audit_stream`), keeps bounded ring-buffer time
series, and turns the raw surfaces into **classified health incidents**:

- **forensic incidents** (streaming audit): a fork, a conflicting
  (sender, slot) value, a commit-monotonicity violation, or overload /
  spoof attribution raises an incident seconds after the evidence lands
  in a journal segment — deduplicated by ``(kind, subject)`` so one
  equivocating node is ONE incident no matter how many slots it poisons
  or how many poll ticks observe it;
- **SLO incidents** (rule engine): per-node epoch lag vs the cluster
  head (straggler score), mempool occupancy, pump/VID backlog pressure,
  degrade engagement, scrape reachability, cluster epochs/s floor and
  phase-p99 ceiling.  Rules carry **hysteresis** — a breach must hold
  for ``engage_ticks`` consecutive ticks to alarm and must clear for
  ``clear_ticks`` ticks to re-arm — so a flapping signal cannot
  alarm-storm.  Each engagement episode raises exactly one incident.
- **perf-drift incidents** (the performance sentinel): given a frozen
  same-host profile (``bench.py --freeze-perf-profile`` →
  ``--perf-profile PATH``), every tick computes each node's live
  per-segment mean cost from the *delta* between consecutive
  ``/metrics`` scrapes (:func:`hbbft_tpu.obs.perf.segment_means`) and
  compares it against the profile.  The worst live/profile mean ratio
  is the ``perf_drift_ratio`` signal; a built-in
  ``perf_drift_ratio<=perf_ratio`` rule rides the same hysteresis +
  episode machinery and raises ``perf_regression`` incidents — a hot
  path that got 2× slower alarms online, a noisy single window does
  not (segments below ``perf_min_events`` events per window are
  ignored).

SLO rule syntax (``--slo``, repeatable): ``signal<=limit`` or
``signal>=limit``, e.g. ``--slo "epochs_per_s>=0.5"`` (cluster floor),
``--slo "p99_s<=2.0"`` (cluster epoch-phase p99 ceiling, seconds),
``--slo "epoch_lag<=3"`` (per-node straggler ceiling), ``--slo
"mempool_frac<=0.9"``, ``--slo "pump_backlog_frac<=1.0"``, ``--slo
"vid_pending<=64"``.  Per-node rules evaluate once per target; cluster
rules once per tick.

Incidents are emitted as wire-registered
:class:`~hbbft_tpu.obs.flight.HealthIncident` records into the
watchtower's own flight journal (``--journal-out``) — the online
detection trail is as durable and auditable as the evidence it points
at — and the aggregated cluster document is served on ``--serve-port``
as ``/health`` (the machine-readable headroom document the future
adaptive controller consumes).

Scrape fan-out is bounded: at most ``--scrape-workers`` concurrent
target polls, each with its own timeout, and a wedged or dead target
counts ``hbbft_health_scrape_failures_total{target}`` instead of
stalling the loop.

The core (:class:`Watchtower`) is clock-free by contract: ``tick(now,
snaps)`` takes the caller's clock and (optionally) pre-fetched
snapshots, so the chaos campaign drives it with virtual time and tests
drive it with a scripted clock; only the CLI loop reads wall clock.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait as _futures_wait
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.obs.audit_stream import (
    IncrementalAuditor,
    JournalTailer,
    extract_incidents,
)
from hbbft_tpu.obs.http import http_get
from hbbft_tpu.obs.metrics import (
    Registry, histogram_quantile, parse_prometheus_text,
)
from hbbft_tpu.obs.perf import segment_means

Target = Tuple[str, int]

#: per-node SLO rules every watchtower runs even with no ``--slo`` flags
#: (conservative enough that a clean healthy cluster never alarms)
DEFAULT_SLOS = ("epoch_lag<=6", "mempool_frac<=0.95")

#: the phase whose cluster-summed histogram backs the ``p99_s`` signal
P99_PHASE = "epoch"


# ===========================================================================
# SLO rules
# ===========================================================================


@dataclass(frozen=True)
class SloRule:
    """One service-level rule: ``signal op limit``."""

    signal: str
    op: str              # "<=" (ceiling) | ">=" (floor)
    limit: float

    def breached(self, value: float) -> bool:
        return value > self.limit if self.op == "<=" \
            else value < self.limit

    @property
    def text(self) -> str:
        return f"{self.signal}{self.op}{self.limit:g}"


#: signals evaluated per target node (subject = the node); everything
#: else is cluster-scoped (subject = "cluster")
NODE_SIGNALS = frozenset({
    "epoch_lag", "mempool_frac", "pump_backlog_frac", "vid_pending",
    "degrade_active", "perf_drift_ratio",
})


def normalize_perf_profile(doc: Any) -> Dict[str, float]:
    """Accept either a frozen-profile document (``bench.py
    --freeze-perf-profile``: ``{"segments": {seg: {"mean_s": …}}}``) or
    a flat ``{segment: mean_s}`` mapping; return the flat form with
    non-positive baselines dropped (a zero baseline cannot anchor a
    ratio)."""
    segs = doc.get("segments", doc) if isinstance(doc, dict) else {}
    out: Dict[str, float] = {}
    for seg, v in segs.items():
        mean = v.get("mean_s") if isinstance(v, dict) else v
        try:
            mean = float(mean)
        # hblint: disable=fault-swallowed-drop (config parsing: a
        # malformed profile entry is skipped, not an ingress drop)
        except (TypeError, ValueError):
            continue
        if mean > 0:
            out[str(seg)] = mean
    return out


def parse_slo_rule(text: str) -> SloRule:
    """``"epochs_per_s>=0.5"`` → :class:`SloRule` (ValueError on any
    other shape — the two supported operators are the syntax)."""
    for op in ("<=", ">="):
        if op in text:
            signal, _, limit = text.partition(op)
            signal = signal.strip()
            if not signal:
                break
            try:
                return SloRule(signal, op, float(limit))
            # hblint: disable=fault-swallowed-drop (config parsing, not
            # an ingress path: the break falls through to the ValueError
            # below, so nothing is dropped — the caller gets the error)
            except ValueError:
                break
    raise ValueError(
        f"bad SLO rule {text!r}: expected signal<=limit or "
        f"signal>=limit")


# ===========================================================================
# Bounded time series
# ===========================================================================


class Ring:
    """Bounded (t, value) series with the derivations the rules need."""

    def __init__(self, maxlen: int = 64):
        self._buf: "deque[Tuple[float, float]]" = deque(maxlen=maxlen)

    def push(self, t: float, v: float) -> None:
        self._buf.append((t, v))

    @property
    def last(self) -> Optional[float]:
        return self._buf[-1][1] if self._buf else None

    def rate(self) -> Optional[float]:
        """Average per-second delta across the retained window (None
        until two samples exist or time stands still)."""
        if len(self._buf) < 2:
            return None
        (t0, v0), (t1, v1) = self._buf[0], self._buf[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)


# ===========================================================================
# Watchtower
# ===========================================================================


def poll_full(host: str, port: int,
              timeout_s: float = 2.0) -> Optional[dict]:
    """One target's ``{"status":…, "metrics":…, "health":…}`` snapshot,
    None if down.  ``health`` is None (not a failure) for endpoints
    predating the ``/health`` route (old nodes, gateways)."""
    try:
        status = json.loads(http_get(host, port, "/status", timeout_s))
        metrics = parse_prometheus_text(
            http_get(host, port, "/metrics", timeout_s))
    # hblint: disable=fault-swallowed-drop (accounted by the caller: a
    # None snapshot counts hbbft_health_scrape_failures_total{target}
    # and feeds the target_down hysteresis)
    except (OSError, ValueError):
        return None
    health: Optional[dict] = None
    try:
        health = json.loads(http_get(host, port, "/health", timeout_s))
    # hblint: disable=fault-swallowed-drop (benign: /health is optional
    # on old endpoints; the status/metrics surfaces above still feed
    # every signal that predates it)
    except (OSError, ValueError):
        health = None
    return {"status": status, "metrics": metrics, "health": health}


class Watchtower:
    """Bounded-state live health evaluation over a set of obs targets.

    Clock-free core: every public entry point takes ``now`` from the
    caller.  ``scrape()`` (the only I/O) is separable — ``tick(now,
    snaps=...)`` accepts pre-fetched snapshots so deterministic drivers
    (tests, the sim-cell campaign) never touch sockets.
    """

    def __init__(self, targets: List[Target],
                 gateways: Optional[List[Target]] = None, *,
                 journal_roots: Optional[List[str]] = None,
                 slos: Tuple[str, ...] = DEFAULT_SLOS,
                 engage_ticks: int = 2, clear_ticks: int = 2,
                 window: int = 64,
                 scrape_workers: int = 8, scrape_timeout_s: float = 2.0,
                 fetch: Optional[Callable[..., Optional[dict]]] = None,
                 recorder: Any = None,
                 registry: Optional[Registry] = None,
                 max_incidents: int = 4096,
                 max_read_bytes: int = 32 * 2**20,
                 derive_ticks: int = 1,
                 perf_profile: Optional[Dict[str, Any]] = None,
                 perf_ratio: float = 2.0,
                 perf_min_events: int = 20):
        self.targets = list(targets)
        self.gateways = list(gateways or [])
        self.rules = [parse_slo_rule(s) for s in slos]
        # the perf-drift sentinel: a frozen {segment: mean_s} baseline
        # arms a built-in per-node perf_drift_ratio ceiling rule that
        # rides the same hysteresis + episode machinery as every SLO
        self.perf_profile = (normalize_perf_profile(perf_profile)
                             if perf_profile else None)
        self.perf_ratio = float(perf_ratio)
        self.perf_min_events = max(1, int(perf_min_events))
        if self.perf_profile:
            self.rules.append(
                SloRule("perf_drift_ratio", "<=", self.perf_ratio))
        # previous scrape's pump-segment series per target (the drift
        # signal is a scrape-to-scrape delta, never the cumulative
        # totals — startup cost must not poison steady-state means);
        # bounded: one small filtered dict per target
        self._prev_segments: Dict[str, dict] = {}
        self.engage_ticks = max(1, engage_ticks)
        self.clear_ticks = max(1, clear_ticks)
        self.window = window
        self.scrape_timeout_s = scrape_timeout_s
        self.fetch = fetch if fetch is not None else poll_full
        self.recorder = recorder
        self.registry = registry if registry is not None else Registry()
        n_targets = len(self.targets) + len(self.gateways)
        # the scrape fan-out bound: a wedged target occupies one worker
        # for at most its socket timeout, and the tick only waits the
        # overall budget before counting stragglers as failures
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(scrape_workers, max(1, n_targets))),
            thread_name_prefix="hbbft-watch")
        # forensic derivation cadence: polling (feeding new journal
        # bytes, itself bounded by max_read_bytes per segment read)
        # happens every tick, but the full AuditResult derivation +
        # incident extraction may be throttled to every Nth tick —
        # detection lag grows by at most (derive_ticks - 1) intervals,
        # a documented trade for riding along with a hot cluster
        self.derive_ticks = max(1, derive_ticks)
        self.tailer = (JournalTailer(journal_roots,
                                     IncrementalAuditor(max_events=0),
                                     max_read_bytes=max_read_bytes)
                       if journal_roots else None)
        # bounded per-(target, signal) series
        self._series: Dict[Tuple[str, str], Ring] = {}
        # rule hysteresis: (rule text, subject) → counters + episode
        self._rule_state: Dict[Tuple[str, str], Dict[str, int]] = {}
        # incident dedup across ticks: one (kind, subject) forever
        self._seen: "deque[Tuple[str, str]]" = deque(maxlen=max_incidents)
        self._seen_set: set = set()
        self.incidents: "deque[Dict[str, Any]]" = deque(
            maxlen=max_incidents)
        self.ticks = 0
        self._seq = 0
        r = self.registry
        self._c_ticks = r.counter(
            "hbbft_health_ticks_total", "watchtower poll ticks")
        self._c_scrapes = r.counter(
            "hbbft_health_scrapes_total",
            "target scrapes attempted (nodes + gateways)")
        self._c_scrape_fail = r.counter(
            "hbbft_health_scrape_failures_total",
            "target scrapes that failed or timed out, by target",
            labelnames=("target",), max_label_sets=n_targets + 1)
        self._c_incidents = r.counter(
            "hbbft_health_incidents_total",
            "health incidents raised, by classification kind",
            labelnames=("kind",), max_label_sets=32)
        self._g_targets_up = r.gauge(
            "hbbft_health_targets_up",
            "targets that answered the latest scrape")
        self._g_alerts = r.gauge(
            "hbbft_health_active_alerts",
            "SLO rules currently engaged (breach held past hysteresis)")

    # -- scraping (the only I/O in the class) --------------------------------

    def scrape(self) -> Dict[str, Optional[dict]]:
        """Poll every target once, bounded: concurrency-capped pool,
        per-target socket timeouts, and an overall wait budget — one
        wedged node can never stall the loop.  Failures are counted
        per target, never raised."""
        everyone = [("node", h, p) for h, p in self.targets] + \
                   [("gateway", h, p) for h, p in self.gateways]
        futures = {}
        for _kind, host, port in everyone:
            name = f"{host}:{port}"
            self._c_scrapes.inc()
            futures[self._pool.submit(
                self.fetch, host, port, self.scrape_timeout_s)] = name
        # bounded wait: each fetch bounds itself via socket timeouts;
        # the extra second covers scheduling, and anything still
        # running past it is this tick's failure (the worker frees
        # itself when its socket times out)
        _futures_wait(list(futures), timeout=self.scrape_timeout_s + 1.0)
        out: Dict[str, Optional[dict]] = {}
        for fut, name in futures.items():
            snap = None
            if fut.done():
                try:
                    snap = fut.result()
                # hblint: disable=fault-swallowed-drop (accounted just
                # below: the None snapshot counts the per-target
                # scrape-failure metric)
                except Exception:
                    snap = None
            else:
                fut.cancel()
            if snap is None:
                self._c_scrape_fail.labels(target=name).inc()
            out[name] = snap
        self._g_targets_up.set(
            sum(1 for s in out.values() if s is not None))
        return out

    # -- signal derivation ---------------------------------------------------

    def _ring(self, subject: str, signal: str) -> Ring:
        key = (subject, signal)
        ring = self._series.get(key)
        if ring is None:
            # bounded: one ring per (target, signal) pair — both finite
            ring = self._series[key] = Ring(self.window)
        return ring

    def _signals(self, now: float,
                 snaps: Dict[str, Optional[dict]]
                 ) -> Dict[Tuple[str, str], float]:
        """(signal, subject) → value for this tick, updating the ring
        buffers along the way."""
        values: Dict[Tuple[str, str], float] = {}
        node_names = [f"{h}:{p}" for h, p in self.targets]
        chain_lens: Dict[str, int] = {}
        for name in node_names:
            snap = snaps.get(name)
            if snap is None:
                continue
            st = snap.get("status") or {}
            chain_lens[name] = int(st.get("chain_len",
                                          st.get("batches", 0)))
        head = max(chain_lens.values(), default=0)
        for name in node_names:
            snap = snaps.get(name)
            if snap is None:
                continue
            st = snap.get("status") or {}
            hd = snap.get("health") or {}
            room = hd.get("headroom") or {}
            lag = head - chain_lens.get(name, 0)
            values[("epoch_lag", name)] = float(lag)
            mp = room.get("mempool") or {}
            if mp.get("cap"):
                values[("mempool_frac", name)] = float(mp.get("frac", 0))
            pb = room.get("pump_backlog") or {}
            if pb.get("cap"):
                values[("pump_backlog_frac", name)] = float(
                    pb.get("frac", 0))
            if "vid_pending" in room:
                values[("vid_pending", name)] = float(
                    room.get("vid_pending") or 0)
            values[("degrade_active", name)] = float(
                1 if (hd.get("degrade") or {}).get("active")
                or (st.get("degraded") or {}).get("active") else 0)
            drift = self._perf_drift(name, snap.get("metrics") or {})
            if drift is not None:
                values[("perf_drift_ratio", name)] = drift
            self._ring(name, "chain_len").push(
                now, float(chain_lens.get(name, 0)))
        # cluster signals
        self._ring("cluster", "head").push(now, float(head))
        rate = self._ring("cluster", "head").rate()
        if rate is not None:
            values[("epochs_per_s", "cluster")] = rate
        p99 = self._phase_p99(snaps)
        if p99 is not None:
            values[("p99_s", "cluster")] = p99
        return values

    def _phase_p99(self, snaps: Dict[str, Optional[dict]]
                   ) -> Optional[float]:
        """Cluster-summed p99 of the ``epoch`` phase histogram — the
        end-to-end latency ceiling signal."""
        by_le: Dict[float, float] = {}
        for snap in snaps.values():
            if snap is None:
                continue
            series = (snap.get("metrics") or {}).get(
                "hbbft_phase_duration_seconds_bucket") or []
            for labels, value in series:
                if labels.get("phase") != P99_PHASE:
                    continue
                le = float("inf") if labels.get("le") == "+Inf" \
                    else float(labels.get("le", "inf"))
                by_le[le] = by_le.get(le, 0.0) + value
        if not by_le:
            return None
        return histogram_quantile(sorted(by_le.items()), 0.99)

    def _perf_drift(self, name: str,
                    metrics: Dict[str, Any]) -> Optional[float]:
        """Worst live/profile per-segment mean-cost ratio for one node
        this tick, or None (no profile armed, first scrape of the
        target, or no profiled segment saw ``perf_min_events`` events
        since the last scrape).  Ratios come from scrape-to-scrape
        deltas so the signal tracks what the node is doing NOW."""
        if not self.perf_profile:
            return None
        keys = ("hbbft_pump_segment_seconds_sum",
                "hbbft_pump_segment_seconds_count")
        cur = {k: metrics.get(k, []) for k in keys}
        prev = self._prev_segments.get(name)
        self._prev_segments[name] = cur
        if prev is None:
            return None
        worst: Optional[float] = None
        for seg, live in segment_means(cur, prev).items():
            base = self.perf_profile.get(seg)
            if base is None or live["events"] < self.perf_min_events:
                continue
            ratio = live["mean_s"] / base
            if worst is None or ratio > worst:
                worst = ratio
        return worst

    # -- incident plumbing ---------------------------------------------------

    def _raise_incident(self, now: float, kind: str, severity: str,
                        subject: str, detail: str,
                        new: List[Dict[str, Any]],
                        dedup: Optional[Tuple[str, str]] = None) -> None:
        """Record one incident unless its dedup identity already fired.

        ``dedup`` defaults to ``(kind, subject)`` — the forensic
        incidents' contract: one equivocating node is one incident no
        matter how many slots or ticks carry the evidence.  Episodic
        SLO incidents pass an episode-scoped identity instead so a NEW
        engagement after a full clear can alarm again."""
        ident = dedup if dedup is not None else (kind, subject)
        if ident in self._seen_set:
            return
        if len(self._seen) == self._seen.maxlen:
            self._seen_set.discard(self._seen[0])
        self._seen.append(ident)
        self._seen_set.add(ident)
        self._seq += 1
        inc = {"seq": self._seq, "t": now, "kind": kind,
               "severity": severity, "subject": subject,
               "key": f"{ident[0]}:{ident[1]}", "detail": detail}
        self.incidents.append(inc)
        new.append(inc)
        self._c_incidents.labels(kind=kind).inc()
        if self.recorder is not None:
            self.recorder.record_incident(kind, severity, subject,
                                          inc["key"], detail, t=now)

    def _eval_rules(self, now: float,
                    values: Dict[Tuple[str, str], float],
                    snaps: Dict[str, Optional[dict]],
                    new: List[Dict[str, Any]]) -> None:
        """Hysteresis state machine over every (rule, subject) pair."""
        checks: List[Tuple[SloRule, str, float]] = []
        for rule in self.rules:
            if rule.signal in NODE_SIGNALS:
                for (sig, subject), v in values.items():
                    if sig == rule.signal:
                        checks.append((rule, subject, v))
            else:
                v = values.get((rule.signal, "cluster"))
                if v is not None:
                    checks.append((rule, "cluster", v))
        # target reachability rides the same hysteresis: a down target
        # breaches the implicit target_up rule
        down_rule = SloRule("target_up", ">=", 1.0)
        for name, snap in snaps.items():
            checks.append((down_rule, name,
                           0.0 if snap is None else 1.0))
        active = 0
        for rule, subject, value in checks:
            key = (rule.text, subject)
            st = self._rule_state.setdefault(
                key, {"breach": 0, "ok": 0, "active": 0, "episode": 0})
            if rule.breached(value):
                st["breach"] += 1
                st["ok"] = 0
                if not st["active"] and st["breach"] >= self.engage_ticks:
                    st["active"] = 1
                    st["episode"] += 1
                    kind = ("target_down"
                            if rule.signal == "target_up" else
                            "straggler" if rule.signal == "epoch_lag"
                            else "perf_regression"
                            if rule.signal == "perf_drift_ratio"
                            else f"slo_{rule.signal}")
                    self._raise_incident(
                        now, kind, "warn", subject,
                        f"{rule.text} breached: {rule.signal}="
                        f"{value:g} for {st['breach']} ticks",
                        new,
                        dedup=(f"{kind}:ep{st['episode']}", subject))
            else:
                st["ok"] += 1
                st["breach"] = 0
                if st["active"] and st["ok"] >= self.clear_ticks:
                    st["active"] = 0
            active += st["active"]
        self._g_alerts.set(active)

    # -- the tick ------------------------------------------------------------

    def tick(self, now: float,
             snaps: Optional[Dict[str, Optional[dict]]] = None
             ) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the incidents raised THIS tick.

        ``snaps`` defaults to a live :meth:`scrape`; deterministic
        drivers pass their own."""
        if snaps is None:
            snaps = self.scrape()
        self.ticks += 1
        self._c_ticks.inc()
        new: List[Dict[str, Any]] = []
        # streaming forensics first: a fork outranks any SLO signal
        if self.tailer is not None:
            self.tailer.poll()
            if self.ticks % self.derive_ticks == 0 \
                    or self.derive_ticks == 1:
                for fi in extract_incidents(self.tailer.result()):
                    self._raise_incident(
                        now, fi["kind"], fi["severity"], fi["subject"],
                        fi["detail"], new)
        values = self._signals(now, snaps)
        self._eval_rules(now, values, snaps, new)
        self._last_values = values
        self._last_snaps_up = sum(
            1 for s in snaps.values() if s is not None)
        return new

    # -- the served document -------------------------------------------------

    def health_doc(self) -> Dict[str, Any]:
        """Aggregated machine-readable cluster health: verdict, active
        alerts, recent incidents, and the per-signal values the
        adaptive controller steers by."""
        values = getattr(self, "_last_values", {})
        active = [
            {"rule": key[0], "subject": key[1]}
            for key, st in sorted(self._rule_state.items())
            if st["active"]
        ]
        rank = {"ok": 0, "warn": 1, "fault": 2, "fork": 3}
        # warn is CURRENT state (engaged alerts clear when the breach
        # does); fault/fork are forensic evidence — permanent, a fork
        # does not un-happen when the signal recovers
        worst = "warn" if active else "ok"
        for inc in self.incidents:
            if (rank.get(inc["severity"], 0) >= rank["fault"]
                    and rank[inc["severity"]] > rank[worst]):
                worst = inc["severity"]
        return {
            "status": worst,
            "ticks": self.ticks,
            "targets": len(self.targets) + len(self.gateways),
            "targets_up": getattr(self, "_last_snaps_up", 0),
            "active_alerts": active,
            "signals": {
                f"{sig}@{subject}": round(v, 6)
                for (sig, subject), v in sorted(values.items())
            },
            "incidents": list(self.incidents)[-32:],
            "audit": (
                {"verdict": self.tailer.result().verdict,
                 "records": self.tailer.auditor.records_fed,
                 "torn_tails": self.tailer.auditor.torn_tails}
                if self.tailer is not None else None
            ),
        }

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        if self.recorder is not None:
            self.recorder.close()


# ===========================================================================
# CLI
# ===========================================================================


def _serve_health(watch: Watchtower, host: str, port: int):
    """Serve the watchtower's own ``/metrics`` + ``/health`` on a
    background thread (its own asyncio loop — the poll loop is
    synchronous)."""
    import asyncio
    import threading

    from hbbft_tpu.obs.http import ObsServer

    started = threading.Event()
    box: Dict[str, Any] = {}

    def run() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        server = ObsServer(watch.registry, health_fn=watch.health_doc)
        box["addr"] = loop.run_until_complete(server.start(host, port))
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, name="hbbft-watch-http",
                         daemon=True)
    t.start()
    started.wait(timeout=5.0)
    return box.get("addr")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.watch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--targets", default="",
                    help="comma-separated host:port node obs endpoints")
    ap.add_argument("--base-port", type=int, default=0,
                    help="metrics base port (node i at base+i)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--gateways", default="",
                    help="comma-separated host:port gateway endpoints")
    ap.add_argument("--journals", default="",
                    help="comma-separated journal roots to tail through "
                         "the streaming auditor")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="RULE",
                    help="SLO rule (signal<=limit or signal>=limit); "
                         "repeatable; added to the defaults "
                         f"{', '.join(DEFAULT_SLOS)}")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--iterations", type=int, default=0,
                    help="0 = run until interrupted")
    ap.add_argument("--engage-ticks", type=int, default=2)
    ap.add_argument("--clear-ticks", type=int, default=2)
    ap.add_argument("--scrape-workers", type=int, default=8)
    ap.add_argument("--scrape-timeout", type=float, default=2.0)
    ap.add_argument("--perf-profile", default="",
                    metavar="PATH",
                    help="frozen per-segment cost profile (JSON from "
                         "bench.py --freeze-perf-profile); arms the "
                         "perf-drift sentinel")
    ap.add_argument("--perf-ratio", type=float, default=2.0,
                    help="live/profile mean-cost ratio ceiling before "
                         "a perf_regression incident (default 2.0)")
    ap.add_argument("--journal-out", default="",
                    help="directory for the watchtower's own incident "
                         "journal (HealthIncident records)")
    ap.add_argument("--serve-port", type=int, default=0,
                    help="serve the aggregated /health (+ /metrics) "
                         "document on this port")
    ap.add_argument("--json", action="store_true",
                    help="print each tick's new incidents as JSONL")
    args = ap.parse_args(argv)

    from hbbft_tpu.obs.top import parse_targets

    # journal-only mode is legitimate (tail + classify, nothing to
    # scrape): no targets required when --journals is given
    targets: List[Target] = []
    if args.targets or args.base_port:
        targets = parse_targets(args)
    elif not args.journals:
        raise SystemExit("need --targets, --base-port/--nodes, "
                         "or --journals")
    gw_targets: List[Target] = []
    for part in args.gateways.split(","):
        part = part.strip()
        if part:
            host, _, port = part.rpartition(":")
            gw_targets.append((host or "127.0.0.1", int(port)))
    roots = [p.strip() for p in args.journals.split(",") if p.strip()]
    recorder = None
    if args.journal_out:
        from hbbft_tpu.obs.flight import FlightRecorder

        # hblint: disable=det-wall-clock (watchtower CLI: incident
        # timestamps are operator-facing wall clock by design)
        import time as _time

        recorder = FlightRecorder(args.journal_out, "watchtower",
                                  flavor="watch", clock=_time.time)
    profile = None
    if args.perf_profile:
        with open(args.perf_profile, encoding="utf-8") as fh:
            profile = json.load(fh)
    watch = Watchtower(
        targets, gw_targets, journal_roots=roots or None,
        slos=tuple(DEFAULT_SLOS) + tuple(args.slo),
        engage_ticks=args.engage_ticks, clear_ticks=args.clear_ticks,
        scrape_workers=args.scrape_workers,
        scrape_timeout_s=args.scrape_timeout,
        recorder=recorder,
        perf_profile=profile, perf_ratio=args.perf_ratio)
    if args.serve_port:
        addr = _serve_health(watch, "127.0.0.1", args.serve_port)
        print(f"watch: serving /health on {addr}", file=sys.stderr)

    import time

    i = 0
    try:
        while True:
            # hblint: disable=det-wall-clock (CLI poll loop: live
            # polling is wall-clock by nature; the Watchtower core
            # itself is clock-free — tick() takes the caller's clock)
            now = time.time()
            for inc in watch.tick(now):
                line = (json.dumps(inc, sort_keys=True) if args.json
                        else f"[{inc['severity']}] {inc['kind']} "
                             f"{inc['subject']}: {inc['detail']}")
                print(line, flush=True)
            i += 1
            if args.iterations and i >= args.iterations:
                break
            time.sleep(args.interval)
    # hblint: disable=fault-swallowed-drop (interactive exit, not a
    # dropped input: ^C ends the watch loop cleanly)
    except KeyboardInterrupt:
        pass
    doc = watch.health_doc()
    print(f"watch: {doc['status']} — {len(watch.incidents)} incidents "
          f"over {watch.ticks} ticks", file=sys.stderr)
    watch.close()
    return 0 if doc["status"] in ("ok", "warn") else 1


if __name__ == "__main__":
    sys.exit(main())

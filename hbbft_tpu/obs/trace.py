"""Per-transaction causal trace context: follow ONE tx across nodes.

PR 3's spans answer "where did this *epoch's* latency go" per node; the
audit reconstructs causality but discards timing.  Neither can say where
a single transaction's 70 ms went across four processes — the question
"The Latency Price of Threshold Cryptosystems in Blockchains" (PAPERS.md)
shows is the one that names the next optimization.  This module is the
trace-context half of that instrument; :mod:`hbbft_tpu.obs.critpath`
is the offline merge/analysis half.

**Trace context = 16-byte trace id + hop counter.**  The trace id is
*content-derived*: the first 16 bytes of ``sha3_256(tx)`` — the same
digest the mempool dedups on, the client keys its latency map on, and
``TX_ACK``/``TX_COMMIT`` frames already carry.  Deriving the id from the
tx bytes means the context **piggybacks on every existing surface** (the
client's SUBMIT frame carries the tx, the contribution carries the tx,
the committed batch carries the tx) with zero wire-format changes to
consensus traffic; only the journal grows a record type.  The hop
counter is the stage depth along the tx's causal path:

====== ============ ======================================================
hop    stage        journaled by
====== ============ ======================================================
0      ``submit``   client, when the TX frame is written
1      ``ack``      client, when the node's ``ACK_ACCEPTED`` arrives
1      ``ingress``  node, when the event loop admits the tx (mempool add)
2      ``queued``   node, when the pump's worker thread dequeues the input
3      ``commit``   every node, when the batch containing the tx commits
4      ``commit_seen`` client, when the ``TX_COMMIT`` digest arrives
4      ``commit_retrieved`` node (VID mode), when the lazily-retrieved
       payload of a committed commitment resolves
====== ============ ======================================================

A :class:`FlightTrace` record (wire tag ``0x95`` — registered like every
journal record so the wire-completeness checker and ``test_wire`` cover
it) carries one stage crossing.  ``tids`` holds the CONCATENATED 16-byte
trace ids of every tx crossing the stage together — a committed batch of
4096 txs is ONE record with a 64 KiB id vector, not 4096 records, so
MB-scale ingestion stays journal-affordable.

Determinism: trace ids are pure functions of tx bytes (no wall clock,
no ``os.urandom`` — this module is in hblint's determinism scope), and
under the simulator the record timestamps are the deterministic virtual
clock, so two identical-seed runs produce byte-identical journals *and*
byte-identical critical-path reports.  Under sockets the timestamps are
each process's real clock; :mod:`~hbbft_tpu.obs.critpath` estimates the
pairwise clock offsets NTP-style and reports the *bound*, never a point
estimate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List

import hashlib

#: width of one trace id (a sha3-256 prefix: 2^-64 collision odds at
#: a billion in-flight txs — fine for attribution, not for consensus)
TRACE_ID_BYTES = 16

#: stage name → hop counter (causal depth along the tx's path)
STAGE_HOPS = {
    "submit": 0,
    "ack": 1,
    "ingress": 1,
    "queued": 2,
    "commit": 3,
    # VID mode: "commit" is the ordering instant (the epoch committed
    # the (root, cert) commitment); "commit_retrieved" is when the
    # payload itself became readable on the node — the gap between the
    # two is the lazy-retrieval latency, off the ordering critical path
    "commit_retrieved": 4,
    "commit_seen": 4,
}


def trace_id(tx: bytes) -> bytes:
    """The tx's 16-byte trace id (``sha3_256(tx)[:16]`` — the mempool /
    ack / commit digest's prefix, so every existing surface that carries
    the tx or its digest already carries the trace context)."""
    return hashlib.sha3_256(tx).digest()[:TRACE_ID_BYTES]


def tid_of_digest(digest: bytes) -> bytes:
    """Trace id from a full 32-byte tx digest (client side: ``TX_ACK``
    and ``TX_COMMIT`` frames carry the digest, not the tx)."""
    return bytes(digest[:TRACE_ID_BYTES])


def pack_tids(tids: Iterable[bytes]) -> bytes:
    """Concatenate trace ids into one ``FlightTrace.tids`` vector."""
    return b"".join(tids)


def iter_tids(tids: bytes) -> List[bytes]:
    """Split a ``FlightTrace.tids`` vector back into 16-byte ids (a
    trailing partial id — torn write — is dropped; the reader's CRC
    makes that unreachable in practice)."""
    n = len(tids) // TRACE_ID_BYTES
    return [tids[i * TRACE_ID_BYTES:(i + 1) * TRACE_ID_BYTES]
            for i in range(n)]


@dataclass(frozen=True)
class TraceContext:
    """One tx's trace context at one hop (the compact token a stage
    passes forward: 16-byte id + hop counter)."""

    tid: bytes
    hop: int

    def next(self) -> "TraceContext":
        return TraceContext(self.tid, self.hop + 1)


@dataclass(frozen=True)
class FlightTrace:
    """One causal stage crossing of one-or-many txs (journal record,
    wire tag ``0x95``; see module docstring for the stage table).

    ``detail`` is a free-form attribution string (the admitting client
    id at ``ingress``, empty elsewhere); ``(era, epoch)`` is the
    committing epoch for ``commit``/``commit_seen`` stages and the
    node's current key (best effort) for earlier stages."""

    seq: int
    t: float
    stage: str
    era: int
    epoch: int
    hop: int
    detail: str
    tids: bytes

"""Dependency-free labeled metrics registry with Prometheus/JSON exposition.

One :class:`Registry` per node (a :class:`~hbbft_tpu.net.runtime.NodeRuntime`
owns one; standalone pieces create private ones) holds
:class:`Counter`/:class:`Gauge`/:class:`Histogram` metrics, each optionally
labeled.  Exposition is Prometheus text format 0.0.4 (``render_prometheus``)
or JSON (``as_dict``) — no client library, no threads, no globals beyond the
module-level :data:`DEFAULT` registry used by process-wide simulator
counters.

Invariants the registry enforces (tier-1 tested):

- metric names must be valid Prometheus identifiers; registration is
  get-or-create and re-registering with a different kind/labelnames raises;
- label cardinality is capped per metric (``max_label_sets``): overflowing
  label sets collapse into a single ``"_overflow_"`` series (and are counted
  in ``Registry.dropped_label_sets``) instead of growing without bound under
  e.g. Byzantine peers inventing ids;
- histogram buckets must be strictly increasing; the ``+Inf`` bucket is
  implicit;
- HELP text and label values are escaped per the Prometheus text rules
  (``\\``, ``\n``, and ``"`` in label values).

Naming convention (checked by ``tools_check_metrics.py``):
``hbbft_<layer>_<name>`` with layer one of ``net`` (transport), ``node``
(runtime/consensus), ``phase`` (epoch-phase tracer), ``sim`` (simulators).
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

OVERFLOW = "_overflow_"

# default histogram buckets: ms-to-seconds scale, matching consensus phase
# latencies on a localhost cluster through to multi-second large-N epochs
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One labeled series of a metric."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def set(self, v: float) -> None:
        self.value = float(v)

    def get(self) -> float:
        return self.value


class _HistChild:
    __slots__ = ("counts", "sum", "count", "_buckets")

    def __init__(self, buckets: Sequence[float]):
        self._buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self._buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count)] including +Inf."""
        out = []
        acc = 0
        for b, c in zip(self._buckets, self.counts):
            acc += c
            out.append((b, acc))
        out.append((math.inf, acc + self.counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        return histogram_quantile(self.cumulative(), q)


class Metric:
    """Base: a named family of labeled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 registry: Optional["Registry"] = None,
                 max_label_sets: int = 256):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_label_sets = max_label_sets
        self.registry = registry
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.labelnames:
            # an unlabeled metric always exposes its (zero) sample — a
            # scraper must be able to distinguish "0 so far" from "metric
            # doesn't exist" (labeled metrics expose series as labels
            # appear, or via explicit pre-init like fault_counter)
            self._child(())

    def _new_child(self):
        return _Child()

    def _child(self, labelvalues: Tuple[str, ...]):
        child = self._children.get(labelvalues)
        if child is None:
            if (len(self._children) >= self.max_label_sets
                    and labelvalues != (OVERFLOW,) * len(self.labelnames)):
                # cardinality cap: collapse into the overflow series
                if self.registry is not None:
                    self.registry.dropped_label_sets += 1
                return self._child((OVERFLOW,) * len(self.labelnames))
            child = self._new_child()
            self._children[labelvalues] = child
        return child

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name")
            values = tuple(str(kv[ln]) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}"
            )
        return self._child(values)

    def series(self) -> List[Tuple[Dict[str, str], Any]]:
        return [
            (dict(zip(self.labelnames, lv)), child)
            for lv, child in sorted(self._children.items())
        ]

    # -- unlabeled conveniences ---------------------------------------------

    def _default(self):
        return self._child(())


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, v: float) -> None:
        """Internal view support (attribute-API shims); not for new code."""
        self._default().set(v)

    def value(self, **kv) -> float:
        if kv:
            return self.labels(**kv).get()
        return self._default().get()

    def total(self) -> float:
        return sum(c.get() for c in self._children.values())


class Gauge(Metric):
    kind = "gauge"

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(-amount)

    def value(self, **kv) -> float:
        if kv:
            return self.labels(**kv).get()
        return self._default().get()


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 registry: Optional["Registry"] = None,
                 max_label_sets: int = 256):
        buckets = tuple(float(b) for b in buckets)
        if not buckets:
            raise ValueError("histogram needs at least one bucket")
        for lo, hi in zip(buckets, buckets[1:]):
            if not lo < hi:
                raise ValueError(
                    f"histogram buckets must be strictly increasing: "
                    f"{lo!r} !< {hi!r}"
                )
        if buckets[-1] == math.inf:
            buckets = buckets[:-1]  # +Inf is implicit
        self.buckets = buckets
        super().__init__(name, help, labelnames, registry=registry,
                         max_label_sets=max_label_sets)

    def _new_child(self):
        return _HistChild(self.buckets)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)


class Registry:
    """A set of metrics with shared exposition.

    ``counter``/``gauge``/``histogram`` are get-or-create: registering the
    same name again returns the existing metric (so independent components
    can share a series), but a kind or labelnames mismatch raises — two
    subsystems silently disagreeing about a metric is a bug.
    """

    def __init__(self):
        self._metrics: "Dict[str, Metric]" = {}
        self._callbacks: List[Callable[[], None]] = []
        self._lock = threading.Lock()
        self.dropped_label_sets = 0

    # -- registration --------------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != cls.kind or (
                    existing.labelnames != tuple(labelnames)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}, requested "
                        f"{cls.kind}{tuple(labelnames)}"
                    )
                want = kw.get("buckets")
                if want is not None and tuple(
                    b for b in (float(x) for x in want) if b != math.inf
                ) != existing.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {existing.buckets}, requested "
                        f"{tuple(want)}"
                    )
                return existing
            metric = cls(name, help, labelnames, registry=self, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = (), **kw) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames, **kw)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (), **kw) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames, **kw)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **kw) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets, **kw)

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def register_callback(self, fn: Callable[[], None]) -> None:
        """``fn`` runs before every exposition — the hook for gauges whose
        value is derived state (queue depths, peer epochs) rather than
        incrementally maintained."""
        self._callbacks.append(fn)

    def collect(self) -> List[Metric]:
        for fn in self._callbacks:
            fn()
        return [self._metrics[n] for n in sorted(self._metrics)]

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self) -> str:
        out: List[str] = []
        for m in self.collect():
            out.append(f"# HELP {m.name} {escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for labels, child in m.series():
                base = _render_labels(labels)
                if m.kind == "histogram":
                    for le, cum in child.cumulative():
                        ls = _render_labels(dict(labels, le=_fmt(le)))
                        out.append(f"{m.name}_bucket{ls} {cum}")
                    out.append(f"{m.name}_sum{base} {_fmt(child.sum)}")
                    out.append(f"{m.name}_count{base} {child.count}")
                else:
                    out.append(f"{m.name}{base} {_fmt(child.get())}")
        out.append("")
        return "\n".join(out)

    def as_dict(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {}
        for m in self.collect():
            series = []
            for labels, child in m.series():
                if m.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "sum": child.sum,
                        "count": child.count,
                        "buckets": [
                            [("+Inf" if le == math.inf else le), cum]
                            for le, cum in child.cumulative()
                        ],
                    })
                else:
                    series.append({"labels": labels, "value": child.get()})
            doc[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return doc

    def render_json(self) -> str:
        return json.dumps(self.as_dict())


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


class MetricAttr:
    """Descriptor: a numeric attribute view over an unlabeled metric held
    on the instance (``backing`` names the instance attribute storing the
    metric).  This is the shim that keeps pre-registry attribute APIs —
    ``stats.frames_sent += 1`` — working while the registry carries the
    series, without a hand-written property pair per field."""

    def __init__(self, backing: str, cast=int):
        self.backing = backing
        self.cast = cast

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return self.cast(getattr(obj, self.backing).value())

    def __set__(self, obj, v) -> None:
        getattr(obj, self.backing).set(v)


# -- shared helpers ----------------------------------------------------------


def fault_counter(registry: Registry) -> Counter:
    """The per-FaultKind Byzantine-evidence counter, with every variant
    pre-initialized to 0 so exposition always shows the complete label set
    (``tools_check_metrics.py`` asserts this coverage)."""
    from hbbft_tpu.fault_log import FaultKind

    c = registry.counter(
        "hbbft_node_faults_total",
        "Byzantine faults observed, by FaultKind variant",
        labelnames=("kind",),
        max_label_sets=len(FaultKind) + 1,
    )
    for k in FaultKind:
        c.labels(kind=k.name)
    return c


def histogram_quantile(cumulative: Iterable[Tuple[float, float]],
                       q: float) -> float:
    """Prometheus-style quantile estimate from cumulative ``(le, count)``
    pairs (last pair is the ``+Inf`` bucket): linear interpolation within
    the bucket containing the target rank; the +Inf bucket reports its
    lower bound."""
    pairs = sorted(cumulative)
    if not pairs:
        return math.nan
    total = pairs[-1][1]
    if total <= 0:
        return math.nan
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum >= rank:
            if le == math.inf:
                return prev_le
            if cum == prev_cum:
                return le
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le


def parse_prometheus_text(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse exposition text back into ``{name: [(labels, value)]}`` —
    enough for ``obs.top`` and for round-trip tests; histogram series
    appear under their ``_bucket``/``_sum``/``_count`` names."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$",
                     line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, _g, labelstr, valstr = m.groups()
        labels: Dict[str, str] = {}
        if labelstr:
            for lm in re.finditer(
                r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"', labelstr
            ):
                # unescape left-to-right in one pass: sequential
                # .replace() calls corrupt values like 'C:\\new' (the
                # unescaped backslash joins the following 'n')
                labels[lm.group(1)] = re.sub(
                    r"\\(.)",
                    lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                    lm.group(2),
                )
        if valstr == "+Inf":
            value = math.inf
        elif valstr == "-Inf":
            value = -math.inf
        else:
            value = float(valstr)
        out.setdefault(name, []).append((labels, value))
    return out


#: process-wide default registry — used only by components with no natural
#: owner (the simulator-side wire_size failure counter); everything tied to
#: a node goes on that node's own registry
DEFAULT = Registry()

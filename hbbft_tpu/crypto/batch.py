"""Randomized batch verification of threshold-crypto shares on TPU.

SURVEY §3.5 ranks pairing-based share verification the #1 network-wide hot
loop: every coin flip makes every node verify up to N signature shares (one
pairing each), O(N²) pairings per round.  The standard randomized-linear-
combination trick turns N pairing checks into two MSMs plus ONE two-pairing
check:

    valid ∀i:  e(g1, σ_i) = e(pk_i, h)            (signature shares)
    ⟸  e(g1, Σ rᵢσ_i) = e(Σ rᵢ pk_i, h)           for random 128-bit rᵢ
        (soundness 2⁻¹²⁸: a cheating share survives only if the rᵢ hit a
        nontrivial linear relation)

    valid ∀i:  e(d_i, h) = e(pk_i, W)              (decryption shares)
    ⟸  e(Σ rᵢ d_i, h) = e(Σ rᵢ pk_i, W)

The MSMs — the scalar-multiplication-heavy part — run batched on the device
(:mod:`hbbft_tpu.ops.gcurve` ladders over the limbed field); the final two
pairings run on the host oracle.  On a batch failure the caller falls back
to per-share verification to assign blame (same pattern as the optimistic
combine in :mod:`hbbft_tpu.protocols.threshold_sign`).
"""

from __future__ import annotations

import random
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.crypto import bls12_381 as c
from hbbft_tpu.ops import gcurve as G

_RAND_BITS = 128
# ladder width for the windowed device ladders: the 129 bits of an odd
# 128-bit randomizer (or GLV half-scalar), rounded up to the 4-bit window
_WINDOW_BITS = 132


# Above this many ladder rows the MSM is compute-bound and the 13-bit VPU
# field wins (its 900-MAC schoolbook limb product is ~100× lighter per value
# than the MXU formulation's one-hot matmul); below it the run is
# launch-bound and the MXU field's fewer/fused kernels win.  Measured
# crossover on TPU v5e: coin256 (B=512) 2.1× faster on mxu, dkg256
# (B=16384) 3.4× faster on lazy.
MXU_MAX_BATCH = 2048


def _field_rep(size: int):
    """Device field backend for an MSM ladder of ``size`` rows.

    ``HBBFT_FIELD_BACKEND=lazy|mxu`` forces one; default picks by batch
    size (see MXU_MAX_BATCH).  Both are exact; speed choice only."""
    import os

    forced = os.environ.get("HBBFT_FIELD_BACKEND")
    if forced not in (None, "", "mxu", "lazy"):
        raise ValueError(
            f"HBBFT_FIELD_BACKEND={forced!r}: expected 'mxu' or 'lazy'"
        )
    use_mxu = (
        forced == "mxu"
        or (not forced and size <= MXU_MAX_BATCH)
    )
    if not use_mxu:
        from hbbft_tpu.ops import fp381 as rep

        return rep, G.LAZY_FP_OPS, G.LAZY_FP2_OPS
    from hbbft_tpu.ops import fp381_mxu as rep

    return rep, G.MXU_FP_OPS, G.MXU_FP2_OPS


class _MsmCache:
    """Jitted MSM launchers per (group, padded batch size).

    With ``mesh`` set, every ladder runs ``shard_map``-ped with its batch
    (row) axis sharded over the mesh — the MSM rows are independent, so the
    crypto phase of an epoch scales across chips with no collectives; the
    host fold sees the gathered result exactly as in the single-device
    case.  ``use_mesh(mesh)`` swaps the module-global cache.
    """

    def __init__(self, mesh=None):
        self._fns = {}
        self.mesh = mesh

    def _get(self, group: str, size: int):
        # one jitted LADDER per (group, padded size); the final fold over
        # the ≤size ladder outputs happens on the host — a handful of bigint
        # adds, versus log2(size) more big point_add graphs to compile.
        # The ladder runs a LAZY (non-canonical) field: randomizers are
        # 128-bit, which is exactly the regime where its digit-based zero
        # checks are sound (see ops/fp381.py); host fold canonicalizes.
        # I/O is ONE stacked array each way: per-coordinate transfers cost a
        # full tunnel round-trip each (~100 ms) on the remote-chip setup.
        import os

        rep, fp_ops, fp2_ops = _field_rep(size)
        # HBBFT_PLAIN_LADDER=1 forces the plain bitwise ladder: its XLA
        # graph compiles ~8× faster than the windowed one (30 s vs 250 s
        # cold for g2@8 on the CPU backend) at a ~1.5× runtime cost — the
        # test suite sets it (tests/conftest.py) so cold-cache suite runs
        # are not dominated by ladder compiles; production (TPU bench)
        # keeps the windowed default.  Both are exact.
        plain = os.environ.get("HBBFT_PLAIN_LADDER") == "1"
        # the resolved backend/ladder style is part of the key: flipping
        # the env vars mid-process must not serve a stale ladder
        key = (group, size, rep.__name__, plain)
        if key not in self._fns:
            import jax
            import jax.numpy as jnp
            # windowed ladder wins in the launch-bound small-batch regime;
            # at large B its one-hot table selects cost more than the adds
            # they save, so the plain bitwise ladder is faster there
            lad = (
                G.scalar_mul_lazy_window
                if size <= MXU_MAX_BATCH and not plain
                else G.scalar_mul_lazy
            )

            def pack(flat, oinf):
                # the inf flags ride as one extra row so the result is ONE
                # device→host transfer, and everything ships as int16 (lazy
                # digits fit: ≤ 2^13 for the 13-bit field, ≤ 2^8 for the
                # MXU field) — transfers cross a bandwidth-limited tunnel
                nl = flat.shape[-1]
                inf_row = jnp.pad(
                    oinf.astype(flat.dtype)[:, None], ((0, 0), (0, nl - 1))
                )
                return jnp.concatenate(
                    [flat, inf_row[None]], 0
                ).astype(jnp.int16)

            if group == "g1":

                def ladder(stacked, b, inf):
                    stacked = stacked.astype(jnp.int32)
                    b = b.astype(jnp.int32)
                    pt = (stacked[0], stacked[1], stacked[2])
                    out, oinf = lad(fp_ops, pt, b, inf)
                    return pack(jnp.stack(out), oinf)

            else:

                def ladder(stacked, b, inf):
                    stacked = stacked.astype(jnp.int32)
                    b = b.astype(jnp.int32)
                    pt = (
                        (stacked[0], stacked[1]),
                        (stacked[2], stacked[3]),
                        (stacked[4], stacked[5]),
                    )
                    out, oinf = lad(fp2_ops, pt, b, inf)
                    flat = jnp.stack(
                        [out[0][0], out[0][1], out[1][0], out[1][1],
                         out[2][0], out[2][1]]
                    )
                    return pack(flat, oinf)

            if self.mesh is not None and size % self.mesh.devices.size == 0:
                from hbbft_tpu.util import shard_map_compat
                shard_map = shard_map_compat()
                from jax.sharding import PartitionSpec as P

                axes = tuple(self.mesh.axis_names)
                ladder = shard_map(
                    ladder,
                    mesh=self.mesh,
                    # rows (the batch axis) shard over the mesh; there is
                    # no cross-row communication inside a ladder
                    in_specs=(P(None, axes), P(axes), P(axes)),
                    out_specs=P(None, axes),
                    check_vma=False,
                )
            self._fns[key] = (jax.jit(ladder), rep)
        return self._fns[key]

    @staticmethod
    def _pad(n: int) -> int:
        size = 1
        while size < n:
            size *= 2
        return size

    def _msm_dispatch(self, group: str, points, scalars):
        """Enqueue a ladder on the device, returning a handle for
        :meth:`_msm_collect`.  Dispatch/collect split so independent MSMs
        (e.g. the G1+G2 pair of a signature batch-verify) overlap on the
        device instead of serializing on the result transfer."""
        import jax.numpy as jnp

        size = self._pad(len(points))
        fn, rep = self._get(group, size)
        pts = list(points) + [None] * (size - len(points))
        sc = list(scalars) + [0] * (size - len(scalars))
        if group == "g1":
            stacked = np.stack(G.g1_to_device(pts, rep=rep))  # (3, B, NL)
        else:
            stacked = np.stack([
                x for coord in G.g2_to_device(pts, rep=rep) for x in coord
            ])  # (6, B, NL)
        stacked = stacked.astype(np.int16)  # canonical limbs fit; 2× less
        bits = jnp.asarray(
            G.scalars_to_bits(sc, nbits=_WINDOW_BITS).astype(np.uint8)
        )
        base_inf = jnp.asarray(np.array([p is None for p in pts]))
        packed = fn(jnp.asarray(stacked), bits, base_inf)
        return (group, rep, len(points), packed)

    def _msm_collect(self, handle):
        group, rep, n_pts, packed = handle
        # ONE bulk device→host transfer for all coordinates + the inf flags
        packed = np.asarray(packed)
        out = packed[:-1]
        inf = packed[-1, :, 0].astype(bool)
        if group == "g1":
            host_pts = G.g1_from_device_batch(
                (out[0], out[1], out[2]), rep=rep
            )
            host_add = c.g1_add
        else:
            host_pts = G.g2_from_device_batch(
                ((out[0], out[1]), (out[2], out[3]), (out[4], out[5])),
                rep=rep,
            )
            host_add = c.g2_add
        acc = None  # lazy coords of ∞ entries are garbage —
        for i in range(n_pts):  # the inf flag, not Z, is authoritative
            if inf[i]:
                continue
            acc = host_add(acc, host_pts[i])
        return acc

    def _msm(self, group: str, points, scalars):
        return self._msm_collect(self._msm_dispatch(group, points, scalars))

    def msm_g1(self, points, scalars):
        """points: host Jacobian G1 points; scalars: ints. → host point."""
        return self._msm("g1", points, scalars)

    def msm_g2(self, points, scalars):
        return self._msm("g2", points, scalars)

    def _mul_batch_dispatch(self, group: str, points, scalars, endo, lam):
        """Enqueue ONE endomorphism-split ladder for full-range (mod r)
        scalars, returning a handle for :meth:`_mul_batch_collect`.

        The lazy ladder is sound only below 2^128 (see ops/fp381.py), so
        each scalar splits against the group's endomorphism eigenvalue
        ``lam``: s = a + b·λ with a = s mod λ, b = s ÷ λ — both positive
        and < 2^128 — and s·P = a·P + b·endo(P), where ``endo`` costs one
        or two host field muls per point (G1: GLV φ via β·x,
        ``bls12_381.LAMBDA_G1``; G2: GLS ψ² via Fp coordinate norms,
        ``bls12_381.LAMBDA_G2``).  ONE 128-bit ladder launch over the
        doubled batch [P…, endo(P)…] replaces a 255-bit ladder; the final
        a·P + b·endo(P) add runs on the host (complete addition — the two
        terms can collide as ±Q only on an algebraic coincidence).

        The dispatch/collect split is what the split device encrypt's
        chunk pipeline rides: G2 ladders of chunk i run on the device
        while the host hashes chunk i+1."""
        import jax.numpy as jnp

        B = len(points)
        size = self._pad(B)
        fn, rep = self._get(group, 2 * size)
        pts = list(points) + [None] * (size - B)
        sc = [s % c.R for s in scalars] + [0] * (size - B)
        a = [s % lam for s in sc]
        b = [s // lam for s in sc]
        phi = [endo(p) for p in pts]

        if group == "g1":
            stacked = np.stack(G.g1_to_device(pts + phi, rep=rep))
        else:
            stacked = np.stack([
                x for coord in G.g2_to_device(pts + phi, rep=rep)
                for x in coord
            ])
        stacked = stacked.astype(np.int16)
        bits = jnp.asarray(
            G.scalars_to_bits(a + b, nbits=_WINDOW_BITS).astype(np.uint8)
        )
        base_inf = jnp.asarray(np.array([p is None for p in pts] * 2))
        packed = fn(jnp.asarray(stacked), bits, base_inf)
        return (group, rep, B, size, packed)

    def _mul_batch_collect(self, handle):
        """Block on a :meth:`_mul_batch_dispatch` handle; returns host
        Jacobian points (None = infinity), index-aligned with the
        dispatched points."""
        group, rep, B, size, packed = handle
        packed = np.asarray(packed)  # ONE bulk transfer; the device fence
        out = packed[:-1]  # inf flags ride in the last row
        inf_h = packed[-1, :, 0].astype(bool)
        if group == "g1":
            host_pts = G.g1_from_device_batch(
                (out[0], out[1], out[2]), rep=rep
            )  # a·P rows, then b·endo(P)
            host_add = c.g1_add
        else:
            host_pts = G.g2_from_device_batch(
                ((out[0], out[1]), (out[2], out[3]), (out[4], out[5])),
                rep=rep,
            )
            host_add = c.g2_add
        res = []
        for i in range(B):
            lo = None if inf_h[i] else host_pts[i]
            hi = None if inf_h[size + i] else host_pts[size + i]
            res.append(host_add(lo, hi))
        return res

    def g1_mul_batch(self, points, scalars):
        """Batched G1 scalar-mul for FULL-RANGE (mod r) scalars via GLV
        (see :meth:`_mul_batch_dispatch`)."""
        return self._mul_batch_collect(
            self._mul_batch_dispatch(
                "g1", points, scalars, c.g1_endo, c.LAMBDA_G1
            )
        )

    def g2_mul_batch(self, points, scalars):
        """Batched G2 scalar-mul for FULL-RANGE (mod r) scalars via the
        GLS ψ² split (see :meth:`_mul_batch_dispatch`) — the W-ladder of
        the split device encrypt."""
        return self._mul_batch_collect(
            self._mul_batch_dispatch(
                "g2", points, scalars, c.g2_psi2, c.LAMBDA_G2
            )
        )


_CACHES: Dict[Optional[object], _MsmCache] = {}
_CACHE = _CACHES.setdefault(None, _MsmCache())


def use_mesh(mesh) -> None:
    """Route all MSM ladders through ``mesh`` (row-sharded ``shard_map``;
    see :class:`_MsmCache`).  Pass ``None`` to return to single-device.
    Caches are kept per mesh, so toggling back and forth never re-pays
    ladder compiles (minutes each on the CPU backend)."""
    global _CACHE
    _CACHE = cache_for(mesh)


def cache_for(mesh) -> _MsmCache:
    """The per-mesh ladder cache (created on first use, then reused).

    The explicit-cache route for callers that hold a mesh of their own —
    the sharded verify/decrypt entry points in :mod:`hbbft_tpu.parallel.
    mesh` pin the cache returned here instead of reading the module-global
    ``_CACHE``, so an epoch driver's mesh and the crypto cache's mesh are
    one object and can never disagree."""
    if mesh not in _CACHES:
        _CACHES[mesh] = _MsmCache(mesh=mesh)
    return _CACHES[mesh]


def current_mesh():
    """The mesh the module-global entry points currently route through
    (``None`` = single-device).  Benches record this next to their
    results so ``--compare`` only gates equal-mesh runs."""
    return _CACHE.mesh


class routed_mesh:
    """Scope-bound :func:`use_mesh`: route the module-global MSM entry
    points through ``mesh`` inside the ``with`` block, restoring the
    previous routing on exit.  The epoch driver wraps its crypto phases
    in this so the mesh handed to ``BatchedHoneyBadgerEpoch(mesh=...)``
    and the mesh consulted by :func:`device_encrypt_worthwhile` are the
    same object — the two could previously be set independently and
    disagree.  Re-entrant; a no-op when ``mesh`` is already routed."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        global _CACHE
        self._prev = _CACHE
        _CACHE = cache_for(self.mesh)
        return _CACHE

    def __exit__(self, *exc):
        global _CACHE
        _CACHE = self._prev
        return False


# --------------------------------------------------------------------------
# Batched TPKE decryption (HoneyBadger epoch hot loop)
# --------------------------------------------------------------------------


# crossover for the decrypt batch: with the master-scalar fold the cost is
# ONE scalar-mul per ciphertext, so the device ladder only pays off once the
# ciphertext count alone is large (C++ oracle ≈ 0.44 ms/mul: A=4096 is
# 1.8 s on host vs ~2.4 s for the ladder launch — host still wins there)
DEVICE_DECRYPT_MIN_BATCH = 8192


# id(pks) → (pks, share-index tuple, master).  The strong pks reference
# keeps the id from being recycled while the entry lives (same pattern as
# parallel/aba._MASTER_CACHE); bounded for long multi-network processes.
# The O(t²) Lagrange-coefficient interpolation costs ~0.6 s per call at
# t=1365 — recomputing it every epoch would dominate the decrypt phase.
_MASTER_CACHE = {}
_MASTER_CACHE_MAX = 64


def _master_for(pks, items) -> int:
    from hbbft_tpu.crypto import tc

    # key on the share VALUES too (cheap tuple hash) — a share refresh at
    # the same indices must not serve a stale master
    key_shares = tuple((i, sk.scalar) for i, sk in items)
    hit = _MASTER_CACHE.get(id(pks))
    if hit is not None and hit[0] is pks and hit[1] == key_shares:
        return hit[2]
    master = tc.master_secret_from_shares(key_shares)
    if len(_MASTER_CACHE) >= _MASTER_CACHE_MAX:
        _MASTER_CACHE.clear()
    _MASTER_CACHE[id(pks)] = (pks, key_shares, master)
    return master


def batch_tpke_decrypt(pks, cts, secret_shares, cache=None):
    """God-view batched TPKE decryption of many ciphertexts at once.

    ``secret_shares``: (index, SecretKeyShare) pairs, ≥ t+1 of them (the
    first t+1 by index are used, matching ``PublicKeySet.decrypt``'s share
    selection).  Because every share of ciphertext p has the same base
    (D_{p,i} = x_i·U_p), the Lagrange combine collapses to a master-scalar
    fold: mask_p = Σ_i λ_i·x_i·U_p = f(0)·U_p — ONE scalar-mul per
    ciphertext, batched on device above ``DEVICE_DECRYPT_MIN_BATCH``.
    The same documented god-view shortcut as the simulator's once-per-
    proposer decryption (per-node share traffic/verification is the cost
    model's business).  Returns the plaintext list, aligned with ``cts``.

    ``cache``: an explicit :class:`_MsmCache` (from :func:`cache_for`) for
    mesh-pinned callers; defaults to the module-global routing.
    """
    from hbbft_tpu.crypto import tc

    t = pks.threshold()
    items = sorted(secret_shares)[: t + 1]
    if len(items) < t + 1:
        raise ValueError(f"need {t + 1} shares, got {len(items)}")
    if not cts:
        return []
    master = _master_for(pks, items)
    if _device_worthwhile(len(cts), DEVICE_DECRYPT_MIN_BATCH):
        masks = (_CACHE if cache is None else cache).g1_mul_batch(
            [ct.u for ct in cts], [master] * len(cts)
        )
        mask_bytes = [c.g1_to_bytes(m) for m in masks]
    else:
        nat = c._native()
        if nat is not None:
            # the WHOLE decrypt (GLV mask fold + KDF + XOR) is one C call
            # with the GIL released
            return nat.bls_tpke_decrypt_batch(
                master,
                [c.g1_to_bytes(ct.u) for ct in cts],
                [ct.v for ct in cts],
            )
        mask_bytes = [
            c.g1_to_bytes(c.g1_mul(ct.u, master)) for ct in cts
        ]
    out = []
    for ct, mb in zip(cts, mask_bytes):
        stream = tc._kdf_stream(mb, len(ct.v))
        out.append(bytes(a ^ b for a, b in zip(ct.v, stream)))
    return out


def batch_tpke_check_decrypt(pks, payloads, secret_shares, cache=None):
    """Wire-validate + decrypt raw ciphertext payload bytes in one pass —
    the HoneyBadger epoch's parse phase (``Ciphertext.from_bytes`` per
    accepted proposer: canonical/on-curve/subgroup checks for U and W)
    fused with the master-scalar decrypt into ONE native call with the GIL
    released throughout.  Semantics match ``Ciphertext.from_bytes`` then
    :func:`batch_tpke_decrypt` exactly: raises ``ValueError`` on any
    malformed payload (re-parsed per-item for the precise message).
    Returns the plaintext list, aligned with ``payloads``.
    """
    from hbbft_tpu.crypto import tc

    t = pks.threshold()
    items = sorted(secret_shares)[: t + 1]
    if len(items) < t + 1:
        raise ValueError(f"need {t + 1} shares, got {len(items)}")
    if not payloads:
        return []
    nat = c._native()
    # the native call requires exact framing (vlen == len − 294); route
    # only the stragglers to the slow path so one odd payload cannot push
    # the whole epoch back onto per-item Python parsing
    exact_idx = [
        i for i, p in enumerate(payloads)
        if len(p) >= 294
        and int.from_bytes(p[290:294], "big") == len(p) - 294
    ]
    if nat is not None and exact_idx:
        res = nat.bls_tpke_check_decrypt_batch(
            _master_for(pks, items), [payloads[i] for i in exact_idx]
        )
        if res is not None:
            if len(exact_idx) == len(payloads):
                return res
            out = [None] * len(payloads)
            for i, pt in zip(exact_idx, res):
                out[i] = pt
            rest = [i for i in range(len(payloads)) if out[i] is None]
            cts = [tc.Ciphertext.from_bytes(payloads[i]) for i in rest]
            for i, pt in zip(
                rest, batch_tpke_decrypt(pks, cts, secret_shares, cache=cache)
            ):
                out[i] = pt
            return out
    # ground-truth path: per-item parse (raises with the precise error on
    # the first malformed payload), then the batched decrypt
    cts = [tc.Ciphertext.from_bytes(p) for p in payloads]
    return batch_tpke_decrypt(pks, cts, secret_shares, cache=cache)


# --------------------------------------------------------------------------
# Split device TPKE encrypt (the flagship epoch's dominant host phase)
# --------------------------------------------------------------------------
#
# One TPKE encrypt is U = r·g1, mask = r·pk, V = m ⊕ KDF(mask),
# W = r·H_G2(U‖V).  The round-5 one-call native path costs ~920 µs/item at
# N=4096 (BASELINE.md phase table), ~46 % of it hash-to-G2 — the only
# genuinely host-shaped part.  This path splits the batch: the two G1
# ladders and the GLS G2 ladder for ALL proposers run as device MSM
# dispatches, while hash-to-G2 (+ KDF/XOR) stays in a native batch call —
# and the two overlap through chunking: while the device runs chunk i's
# W-ladder, the host hashes chunk i+1 (plus, one level up, the epoch
# pipeline overlaps the whole phase with the previous epoch's ACS).
#
# MEASURED ROOFLINE (single chip — why AUTO routing keeps the host asm):
# per item the split ladders are 4 G1 + 2 G2 lazy-ladder rows of 132
# window bits ≈ 132·(4·18 + 2·55) ≈ 24 000 field row-muls.  The XLA
# lowering of the lazy field measures ~135 ns/row-mul at 8192 rows
# (ops/pallas_fp.py table), and the round-5 dkg256 artifact (2.09 s for a
# 7396-mul GLV ladder = a 14 792-row × 132-bit launch, BENCH_r05.json)
# implies ~60 ns effective — so ONE chip prices an encrypt at
# ~1.4–3.2 ms/item against ~0.5 ms/item for the ADX host asm (40 ns/mul)
# doing the same ladders.  This batch shape is COMPUTE-bound (the regime
# pallas_fp.py's roofline assigns to the host; both device lowerings run
# at ~1 % of VPU peak, bandwidth/fusion-bound), so the single-chip device
# loses ~3–6× and no kernel choice changes that.  The device path wins
# when the MSM rows shard across a mesh (``use_mesh`` — row-sharding is
# collective-free, so 8 chips ≈ 0.2–0.4 ms/item < host asm) or when no
# native oracle exists (pure-Python host is ~100× slower than the ladder).
# AUTO routing (``tc.tpke_encrypt_batch``) encodes exactly that; set
# HBBFT_ENCRYPT_BACKEND=device|native to override.

# below this many items the launch overhead dominates any ladder win
DEVICE_ENCRYPT_MIN_BATCH = 256

# items per pipeline chunk: big enough to amortize dispatch (the G1 ladder
# of a chunk is 4·CHUNK rows), small enough that the host hash of chunk
# i+1 genuinely overlaps the device W-ladder of chunk i
DEVICE_ENCRYPT_CHUNK = 1024


def device_encrypt_worthwhile(n_items: int) -> bool:
    """AUTO-routing policy for the split device encrypt (roofline above):
    device only with a real accelerator AND either a >1-chip mesh routed
    through :func:`use_mesh` (row-sharding beats the host asm) or no
    native oracle (the pure-Python fallback loses to any ladder)."""
    if n_items < DEVICE_ENCRYPT_MIN_BATCH:
        return False
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return False
    if jax.default_backend() == "cpu":
        return False
    mesh = _CACHE.mesh
    if mesh is not None and mesh.devices.size > 1:
        # row-sharding only engages when the mesh size divides the padded
        # ladder row counts (see _MsmCache._get's divisibility guard) —
        # all powers of two here, so e.g. a 3- or 6-chip mesh would
        # silently run the whole MSM on one chip, the regime the roofline
        # above prices BEHIND the host asm.  The G2 ladder has the fewest
        # rows (2·pad(chunk)); if the mesh divides that, it divides the
        # 2×-larger G1 ladder too.
        rows_g2 = 2 * _MsmCache._pad(min(n_items, DEVICE_ENCRYPT_CHUNK))
        if rows_g2 % mesh.devices.size == 0:
            return True
    return c._native() is None


def _g1_to_bytes_batch(pts) -> list:
    """Affine-serialize host Jacobian G1 points with ONE shared field
    inversion (a Montgomery batch-inversion chain over the z coordinates
    — the Python mirror of the native ``g1_write_batch``).  Byte-identical
    to per-point ``c.g1_to_bytes``, which costs a pow-based inversion
    each: at N=4096 the split encrypt serializes 2×4096 points per epoch
    on the host phase the chunk overlap is trying to hide."""
    p = c.P
    idx = [i for i, pt in enumerate(pts) if pt is not None]
    zs = [pts[i][2] % p for i in idx]
    out = [b"\x40" + bytes(96)] * len(pts)
    if not zs:
        return out
    pre = [1] * (len(zs) + 1)
    for i, z in enumerate(zs):
        pre[i + 1] = pre[i] * z % p
    acc = pow(pre[-1], -1, p)  # the chain's single inversion
    for i in range(len(zs) - 1, -1, -1):
        zi = acc * pre[i] % p  # = zs[i]^-1
        acc = acc * zs[i] % p
        x, y, _ = pts[idx[i]]
        zi2 = zi * zi % p
        out[idx[i]] = (
            b"\x00"
            + (x * zi2 % p).to_bytes(48, "big")
            + (y * zi2 % p * zi % p).to_bytes(48, "big")
        )
    return out


def batch_tpke_encrypt_device(pk_point, msgs: Sequence[bytes], rs):
    """Encrypt ``msgs`` to one threshold key with the ladders on the chip.

    ``pk_point``: the public key's G1 Jacobian point; ``rs``: one nonzero
    scalar (mod r) per message, drawn by the caller — byte-identical to
    the one-call native ``bls_tpke_encrypt_batch`` with the same scalars
    (the cross-path equality test asserts it).  Returns ``tc.Ciphertext``
    objects, index-aligned with ``msgs``.

    Phase structure (per DEVICE_ENCRYPT_CHUNK items):
      1. dispatch ALL chunks' G1 ladders up front — rows [g1…, pk…], GLV
         split inside, one launch per chunk;
      2. per chunk: collect U/mask → KDF/XOR V on host → hash-to-G2 in
         ONE native batch call → dispatch the chunk's GLS G2 W-ladder.
         The device runs chunk i's W-ladder while the host hashes i+1;
      3. collect every W-ladder, assemble ciphertexts.
    """
    from hbbft_tpu.crypto import tc

    n = len(msgs)
    if n == 0:
        return []
    if len(rs) != n:
        raise ValueError("need one scalar per message")
    nat = c._native()
    chunk = DEVICE_ENCRYPT_CHUNK
    spans = [(lo, min(lo + chunk, n)) for lo in range(0, n, chunk)]

    g1_handles = [
        _CACHE._mul_batch_dispatch(
            "g1",
            [c.G1_GEN] * (hi - lo) + [pk_point] * (hi - lo),
            list(rs[lo:hi]) * 2,
            c.g1_endo, c.LAMBDA_G1,
        )
        for lo, hi in spans
    ]

    w_handles = []
    us_all: list = []
    vs_all: list = []
    for (lo, hi), h in zip(spans, g1_handles):
        pts = _CACHE._mul_batch_collect(h)
        m = hi - lo
        ser = _g1_to_bytes_batch(pts)  # U + mask rows, one inversion chain
        u_bytes, mask_bytes = ser[:m], ser[m:]
        vs = [
            bytes(
                a ^ b
                for a, b in zip(msg, tc._kdf_stream(mb, len(msg)))
            )
            for msg, mb in zip(msgs[lo:hi], mask_bytes)
        ]
        hins = [
            b"HBBFT-TPKE" + ub + v for ub, v in zip(u_bytes, vs)
        ]
        if nat is not None:
            # host hash phase: one C call, GIL released throughout
            hs = [
                c._g2_from_bytes_trusted(hb)
                for hb in nat.bls_hash_g2_batch(hins)
            ]
        else:
            hs = [c.hash_g2(hin) for hin in hins]
        w_handles.append(
            _CACHE._mul_batch_dispatch(
                "g2", hs, list(rs[lo:hi]), c.g2_psi2, c.LAMBDA_G2
            )
        )
        # store U affine (it is already serialized) so Ciphertext.to_bytes
        # does not re-run a per-point inversion later
        us_all.extend(c._g1_from_bytes_trusted(ub) for ub in u_bytes)
        vs_all.extend(vs)

    ws_all: list = []
    for h in w_handles:
        ws_all.extend(_CACHE._mul_batch_collect(h))
    return [
        tc.Ciphertext(u, v, w)
        for u, v, w in zip(us_all, vs_all, ws_all)
    ]


# --------------------------------------------------------------------------
# DKG commitment evaluation (SyncKeyGen hot loops)
# --------------------------------------------------------------------------
#
# ``BivarCommitment.row`` / ``.evaluate`` cost (t+1)² G1 scalar-muls each —
# per Part and per Ack respectively, so O(N)·(t+1)² and O(N²)·(t+1)²
# network-wide (SURVEY §7 "hard part #3").  Above a batch-size threshold the
# device ladder beats the per-mul C++ oracle; below it, host wins on launch
# overhead.  Both paths are exact, so dispatch is purely a speed choice.

# Round-5 recalibration: the ADX/GLV-accelerated C++ oracle does ~0.15 ms
# per scalar-mul, so the single-chip device ladder only wins past ~16k rows
# (measured: dkg256's 7396-mul row is 1.75 s device vs 1.58 s host).  On a
# mesh (`use_mesh`) the rows shard across chips and the crossover drops;
# this constant governs the single-chip default.
DEVICE_DKG_MIN_BATCH = 16384  # (t+1)²; ~t ≥ 127 → N ≥ ~382 networks


def _device_worthwhile(batch_size: int, min_batch: Optional[int] = None) -> bool:
    if min_batch is None:
        min_batch = DEVICE_DKG_MIN_BATCH
    if batch_size < min_batch:
        return False
    try:
        import jax  # noqa: F401
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return False
    return True


def commitment_row(bivar_com, x: int):
    """``BivarCommitment.row(x)`` with automatic device batching.

    row(x)[j] = Σ_i points[i][j]·x^i — one batched ladder over all
    (i, j), folded over i on the host.
    """
    t1 = bivar_com.degree() + 1
    if not _device_worthwhile(t1 * t1):
        return bivar_com.row(x)
    from hbbft_tpu.crypto.tc import Commitment, R

    xp = [pow(x, i, R) for i in range(t1)]
    flat_pts = [bivar_com.points[i][j] for i in range(t1) for j in range(t1)]
    flat_sc = [xp[i] for i in range(t1) for j in range(t1)]
    res = _CACHE.g1_mul_batch(flat_pts, flat_sc)
    out = []
    for j in range(t1):
        acc = None
        for i in range(t1):
            acc = c.g1_add(acc, res[i * t1 + j])
        out.append(acc)
    return Commitment(out)


def commitment_eval(bivar_com, x: int, y: int):
    """``BivarCommitment.evaluate(x, y)`` with automatic device batching."""
    t1 = bivar_com.degree() + 1
    if not _device_worthwhile(t1 * t1):
        return bivar_com.evaluate(x, y)
    from hbbft_tpu.crypto.tc import R

    xp = [pow(x, i, R) for i in range(t1)]
    yp = [pow(y, j, R) for j in range(t1)]
    flat_pts = [bivar_com.points[i][j] for i in range(t1) for j in range(t1)]
    flat_sc = [xp[i] * yp[j] % R for i in range(t1) for j in range(t1)]
    res = _CACHE.g1_mul_batch(flat_pts, flat_sc)
    acc = None
    for p in res:
        acc = c.g1_add(acc, p)
    return acc


def poly_eval_range(coeffs, n: int):
    """``[f(1), …, f(n)]`` for the Fr polynomial with ``coeffs`` — the
    Shamir share-evaluation inner loop of the DKG (every dealer evaluates
    its row polynomial at all node indices; every acker re-evaluates).

    Consecutive evaluation points admit the finite-difference scheme from
    "An efficient implementation of the Shamir secret sharing scheme"
    (PAPERS.md): seed the difference table with ``deg+1`` Horner
    evaluations, then every further share costs ``deg`` modular
    *additions* instead of ``deg`` modular multiplications.
    """
    from hbbft_tpu.crypto.tc import R

    def horner(x: int) -> int:
        acc = 0
        for coef in reversed(coeffs):
            acc = (acc * x + coef) % R
        return acc

    deg = len(coeffs) - 1
    if n <= deg + 1:
        return [horner(x) for x in range(1, n + 1)]
    seed = [horner(x) for x in range(1, deg + 2)]
    # forward-difference tails: tail[k] = Δᵏf at the newest point
    table = [list(seed)]
    for _ in range(deg):
        prev = table[-1]
        table.append([(prev[i + 1] - prev[i]) % R
                      for i in range(len(prev) - 1)])
    tail = [row[-1] for row in table]
    out = seed
    for _ in range(n - (deg + 1)):
        for k in reversed(range(deg)):
            tail[k] = (tail[k] + tail[k + 1]) % R
        out.append(tail[0])
    return out


def bivar_rows_range(bivar_poly, n: int):
    """``[bivar_poly.row(1), …, bivar_poly.row(n)]`` via per-column
    finite differences (see :func:`poly_eval_range`) — the dealer-side
    share loop of :meth:`SyncKeyGen.generate_part`."""
    from hbbft_tpu.crypto.tc import Poly

    t1 = bivar_poly.degree() + 1
    cols = [
        poly_eval_range([bivar_poly.coeffs[i][j] for i in range(t1)], n)
        for j in range(t1)
    ]
    return [Poly([cols[j][x] for j in range(t1)]) for x in range(n)]


def bivar_commitment(bivar_poly):
    """``BivarPoly.commitment()`` with automatic device batching (fixed-base
    g1^coeff for all (t+1)² coefficients)."""
    t1 = bivar_poly.degree() + 1
    if not _device_worthwhile(t1 * t1):
        return bivar_poly.commitment()
    from hbbft_tpu.crypto.tc import BivarCommitment

    flat_sc = [bivar_poly.coeffs[i][j] for i in range(t1) for j in range(t1)]
    res = _CACHE.g1_mul_batch([c.G1_GEN] * (t1 * t1), flat_sc)
    mat = [[res[i * t1 + j] for j in range(t1)] for i in range(t1)]
    return BivarCommitment(bivar_poly.degree(), mat)


# --------------------------------------------------------------------------
# Cross-epoch batched share generation / verification (the pump's seam)
# --------------------------------------------------------------------------
#
# The epoch-pipelined node runtime (net/scheduler.py) runs several epochs
# concurrently, so one pump iteration can carry the threshold-crypto work
# of many (epoch, proposer) instances at once: ciphertext CCA checks,
# our own decryption-share generation, and t+1-share set verifications.
# The entry points below take the WHOLE batch and route it through the
# best backend — the device MSM ladders above the measured crossover, the
# native host asm below it — and merge the pairing products so the batch
# pays ONE shared final exponentiation instead of one per instance.
# All randomized-linear-combination coefficients are Fiat–Shamir derived
# (hash of the checked material), so the verdicts are deterministic and
# the hblint determinism rules hold.

# an MSM fold below this many rows is launch-bound: the native/host mul
# loop wins (same crossover family as DEVICE_DECRYPT_MIN_BATCH)
DEVICE_FOLD_MIN_BATCH = 8192


def rlc_fold_g1(points, scalars):
    """``Σ rᵢ·Pᵢ`` over host Jacobian G1 points — the MSM of every RLC
    verification — device ladder above :data:`DEVICE_FOLD_MIN_BATCH`,
    per-item host (native asm) muls below it.  Returns a host point or
    ``None`` for the infinity sum."""
    if _device_worthwhile(len(points), DEVICE_FOLD_MIN_BATCH):
        return _CACHE.msm_g1(points, scalars)
    acc = None
    for p, s in zip(points, scalars):
        acc = c.g1_add(acc, c.g1_mul(p, s))
    return acc


def _fs_scalars(seed: bytes, n: int, offset: int = 0):
    """``n`` deterministic Fiat–Shamir 128-bit coefficients (odd, nonzero)
    derived from ``seed`` — the randomizers of every merged check here."""
    import hashlib

    return [
        int.from_bytes(
            hashlib.sha3_256(
                seed + (offset + k).to_bytes(4, "big")
            ).digest()[:16],
            "big",
        )
        | 1
        for k in range(n)
    ]


def batch_decrypt_share_gen(secret_scalar: int, cts, cache=None):
    """One node's decryption shares ``x_i·U_p`` for many ciphertexts in a
    single call (same scalar, many bases).  Value-identical to per-item
    ``SecretKeyShare.decrypt_share(ct, check=False)``; the device ladder
    engages above the decrypt crossover, the native asm below it.
    ``cache`` as in :func:`batch_verify_sig_shares`."""
    from hbbft_tpu.crypto import tc

    if not cts:
        return []
    if _device_worthwhile(len(cts), DEVICE_DECRYPT_MIN_BATCH):
        pts = (_CACHE if cache is None else cache).g1_mul_batch(
            [ct.u for ct in cts], [secret_scalar] * len(cts)
        )
        return [tc.DecryptionShare(p) for p in pts]
    return [
        tc.DecryptionShare(c.g1_mul(ct.u, secret_scalar)) for ct in cts
    ]


def verify_ciphertext_batch(cts) -> list:
    """Per-ciphertext CCA verdicts for many TPKE ciphertexts in ONE merged
    pairing-product check.

    ``e(g1, W_j) == e(U_j, H_j)`` for every j collapses — with FS
    randomizers ``r_j`` — to ``e(g1, Σ r_j·W_j) · Π e(−r_j·U_j, H_j) == 1``
    (k+1 pairings instead of 2k, one shared final exponentiation).  On a
    merged failure each ciphertext is re-checked individually so the
    verdict list is exactly what per-item ``Ciphertext.verify()`` returns.
    """
    import hashlib

    from hbbft_tpu.crypto import tc

    if not cts:
        return []
    if len(cts) == 1:
        return [cts[0].verify()]
    seed = hashlib.sha3_256(
        b"HBBFT-CT-BATCH" + b"".join(ct.to_bytes() for ct in cts)
    ).digest()
    rs = _fs_scalars(seed, len(cts))
    hs = [tc._hash_ciphertext_point(ct.u, ct.v) for ct in cts]
    w_acc = None
    pairs = []
    for ct, h, r in zip(cts, hs, rs):
        w_acc = c.g2_add(w_acc, c.g2_mul(ct.w, r))
        pairs.append((c.g1_neg(c.g1_mul(ct.u, r)), h))
    pairs.append((c.G1_GEN, w_acc))
    if c.pairing_check(pairs):
        return [True] * len(cts)
    return [ct.verify() for ct in cts]


def verify_dec_share_sets(jobs) -> list:
    """Merged verification of many t+1 decryption-share sets — the
    cross-epoch batched call the pipelined pump issues once per iteration.

    ``jobs``: ``(pks, items, ct)`` triples where ``items`` is the
    ``(share_index, DecryptionShare)`` list of one (epoch, proposer)
    instance and ``ct`` its ciphertext.  Each job's own check is the
    Fiat–Shamir RLC of :meth:`ThresholdDecrypt._batch_verify`; the jobs
    merge into ONE pairing-product check (2k pairings, one shared final
    exponentiation — the ``pc8`` regime of the host pairing is ~2.5×
    cheaper than k separate 2-pairing checks).  On a merged failure each
    job is isolated with its own check, so the returned verdict list
    matches the per-job ground truth."""
    import hashlib

    from hbbft_tpu.crypto import tc

    if not jobs:
        return []
    seed = hashlib.sha3_256(
        b"HBBFT-TD-MULTI"
        + b"".join(
            ct.to_bytes() + b"".join(s.to_bytes() for _, s in items)
            for _pks, items, ct in jobs
        )
    ).digest()
    pairs = []
    per_job = []
    for j, (pks, items, ct) in enumerate(jobs):
        h = tc._hash_ciphertext_point(ct.u, ct.v)
        rhos = _fs_scalars(seed, len(items), offset=j * 4096)
        acc_share = rlc_fold_g1([s.point for _, s in items], rhos)
        acc_pk = rlc_fold_g1(
            [pks.public_key_share(i).point for i, _ in items], rhos
        )
        job_pairs = [(c.g1_neg(acc_share), h), (acc_pk, ct.w)]
        per_job.append(job_pairs)
        pairs.extend(job_pairs)
    if len(jobs) == 1 or c.pairing_check(pairs):
        if len(jobs) == 1:
            return [c.pairing_check(per_job[0])]
        return [True] * len(jobs)
    return [c.pairing_check(jp) for jp in per_job]


def batch_verify_sig_shares(
    pairs: Sequence[Tuple[object, object]],
    msg: bytes,
    rng: random.Random,
    cache=None,
) -> bool:
    """All-or-nothing check of (PublicKeyShare, SignatureShare) pairs.

    True ⟹ every share is valid.  False ⟹ at least one share is invalid
    (caller falls back to per-share verification for blame).

    ``cache``: an explicit per-mesh :class:`_MsmCache` (see
    :func:`cache_for`); default is the module-global routing.
    """
    if not pairs:
        return True
    cc = _CACHE if cache is None else cache
    rs = [rng.getrandbits(_RAND_BITS) | 1 for _ in pairs]
    # dispatch both ladders before collecting either — they overlap on
    # the device
    h_sig = cc._msm_dispatch("g2", [s.point for _, s in pairs], rs)
    h_pk = cc._msm_dispatch("g1", [p.point for p, _ in pairs], rs)
    sig_comb = cc._msm_collect(h_sig)
    pk_comb = cc._msm_collect(h_pk)
    h = c.hash_g2(msg)
    if sig_comb is None or pk_comb is None:
        # Σ rᵢσᵢ = ∞ happens only if shares are invalid (or all inputs ∞)
        return sig_comb is None and pk_comb is None
    return c.pairing_check(
        [(c.g1_neg(c.G1_GEN), sig_comb), (pk_comb, h)]
    )


def batch_verify_dec_shares(
    pairs: Sequence[Tuple[object, object]],
    ct,
    rng: random.Random,
    cache=None,
) -> bool:
    """All-or-nothing check of (PublicKeyShare, DecryptionShare) pairs
    against a TPKE ciphertext (U, V, W).  ``cache`` as in
    :func:`batch_verify_sig_shares`."""
    if not pairs:
        return True
    from hbbft_tpu.crypto.tc import _hash_ciphertext_point

    cc = _CACHE if cache is None else cache
    rs = [rng.getrandbits(_RAND_BITS) | 1 for _ in pairs]
    h_d = cc._msm_dispatch("g1", [d.point for _, d in pairs], rs)
    h_pk = cc._msm_dispatch("g1", [p.point for p, _ in pairs], rs)
    d_comb = cc._msm_collect(h_d)
    pk_comb = cc._msm_collect(h_pk)
    h = _hash_ciphertext_point(ct.u, ct.v)
    if d_comb is None or pk_comb is None:
        return d_comb is None and pk_comb is None
    return c.pairing_check(
        [(c.g1_neg(d_comb), h), (pk_comb, ct.w)]
    )

"""Randomized batch verification of threshold-crypto shares on TPU.

SURVEY §3.5 ranks pairing-based share verification the #1 network-wide hot
loop: every coin flip makes every node verify up to N signature shares (one
pairing each), O(N²) pairings per round.  The standard randomized-linear-
combination trick turns N pairing checks into two MSMs plus ONE two-pairing
check:

    valid ∀i:  e(g1, σ_i) = e(pk_i, h)            (signature shares)
    ⟸  e(g1, Σ rᵢσ_i) = e(Σ rᵢ pk_i, h)           for random 128-bit rᵢ
        (soundness 2⁻¹²⁸: a cheating share survives only if the rᵢ hit a
        nontrivial linear relation)

    valid ∀i:  e(d_i, h) = e(pk_i, W)              (decryption shares)
    ⟸  e(Σ rᵢ d_i, h) = e(Σ rᵢ pk_i, W)

The MSMs — the scalar-multiplication-heavy part — run batched on the device
(:mod:`hbbft_tpu.ops.gcurve` ladders over the limbed field); the final two
pairings run on the host oracle.  On a batch failure the caller falls back
to per-share verification to assign blame (same pattern as the optimistic
combine in :mod:`hbbft_tpu.protocols.threshold_sign`).
"""

from __future__ import annotations

import random
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.crypto import bls12_381 as c
from hbbft_tpu.ops import gcurve as G

_RAND_BITS = 128


class _MsmCache:
    """Jitted MSM launchers per (group, padded batch size)."""

    def __init__(self):
        self._fns = {}

    def _get(self, group: str, size: int):
        # one jitted LADDER per (group, padded size); the final fold over
        # the ≤size ladder outputs happens on the host — a handful of bigint
        # adds, versus log2(size) more big point_add graphs to compile.
        # The ladder runs the LAZY (non-canonical) field: randomizers are
        # 128-bit, which is exactly the regime where its digit-based zero
        # checks are sound (see ops/fp381.py); host fold canonicalizes.
        key = (group, size)
        if key not in self._fns:
            import jax

            ops = G.LAZY_FP_OPS if group == "g1" else G.LAZY_FP2_OPS
            self._fns[key] = jax.jit(
                lambda p, b, inf: G.scalar_mul_lazy(ops, p, b, inf)
            )
        return self._fns[key]

    @staticmethod
    def _pad(n: int) -> int:
        size = 1
        while size < n:
            size *= 2
        return size

    def _msm(self, group: str, points, scalars):
        import jax.numpy as jnp

        size = self._pad(len(points))
        pts = list(points) + [None] * (size - len(points))
        sc = list(scalars) + [0] * (size - len(scalars))
        if group == "g1":
            dev = tuple(jnp.asarray(x) for x in G.g1_to_device(pts))
            # bulk device→host: ONE transfer per coordinate array — per-row
            # np.asarray(x[i]) costs a full device round-trip each (≈160 s
            # for 256 G2 points through the tunneled chip vs <1 s bulk)
            to_host = lambda out: tuple(np.asarray(x) for x in out)
            from_host = lambda arrs, i: G.g1_from_device(
                tuple(a[i] for a in arrs)
            )
            host_add = c.g1_add
        else:
            dev = tuple(
                tuple(jnp.asarray(x) for x in coord)
                for coord in G.g2_to_device(pts)
            )
            to_host = lambda out: tuple(
                (np.asarray(re), np.asarray(im)) for (re, im) in out
            )
            from_host = lambda arrs, i: G.g2_from_device(
                tuple((re[i], im[i]) for (re, im) in arrs)
            )
            host_add = c.g2_add
        bits = jnp.asarray(G.scalars_to_bits(sc, nbits=_RAND_BITS + 1))
        base_inf = jnp.asarray(np.array([p is None for p in pts]))
        out, inf = self._get(group, size)(dev, bits, base_inf)
        inf = np.asarray(inf)
        host_arrs = to_host(out)
        acc = None
        for i in range(len(points)):
            if inf[i]:
                continue
            acc = host_add(acc, from_host(host_arrs, i))
        return acc

    def msm_g1(self, points, scalars):
        """points: host Jacobian G1 points; scalars: ints. → host point."""
        return self._msm("g1", points, scalars)

    def msm_g2(self, points, scalars):
        return self._msm("g2", points, scalars)


_CACHE = _MsmCache()


def batch_verify_sig_shares(
    pairs: Sequence[Tuple[object, object]],
    msg: bytes,
    rng: random.Random,
) -> bool:
    """All-or-nothing check of (PublicKeyShare, SignatureShare) pairs.

    True ⟹ every share is valid.  False ⟹ at least one share is invalid
    (caller falls back to per-share verification for blame).
    """
    if not pairs:
        return True
    rs = [rng.getrandbits(_RAND_BITS) | 1 for _ in pairs]
    sig_comb = _CACHE.msm_g2([s.point for _, s in pairs], rs)
    pk_comb = _CACHE.msm_g1([p.point for p, _ in pairs], rs)
    h = c.hash_g2(msg)
    if sig_comb is None or pk_comb is None:
        # Σ rᵢσᵢ = ∞ happens only if shares are invalid (or all inputs ∞)
        return sig_comb is None and pk_comb is None
    return c.pairing_check(
        [(c.g1_neg(c.G1_GEN), sig_comb), (pk_comb, h)]
    )


def batch_verify_dec_shares(
    pairs: Sequence[Tuple[object, object]],
    ct,
    rng: random.Random,
) -> bool:
    """All-or-nothing check of (PublicKeyShare, DecryptionShare) pairs
    against a TPKE ciphertext (U, V, W)."""
    if not pairs:
        return True
    from hbbft_tpu.crypto.tc import _hash_ciphertext_point

    rs = [rng.getrandbits(_RAND_BITS) | 1 for _ in pairs]
    d_comb = _CACHE.msm_g1([d.point for _, d in pairs], rs)
    pk_comb = _CACHE.msm_g1([p.point for p, _ in pairs], rs)
    h = _hash_ciphertext_point(ct.u, ct.v)
    if d_comb is None or pk_comb is None:
        return d_comb is None and pk_comb is None
    return c.pairing_check(
        [(c.g1_neg(d_comb), h), (pk_comb, ct.w)]
    )

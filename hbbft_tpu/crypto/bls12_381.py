"""BLS12-381: fields, curves, pairing — pure-Python ground truth.

The reference's crypto lives in the external ``threshold_crypto`` crate over
``pairing``/``ff`` (BLS12-381); this module is our from-scratch equivalent of
that curve layer.  Design notes:

- Everything derives from the BLS parameter ``x = -0xd201000000010000``:
  p, r, and both cofactors are computed from the BLS12 family formulas at
  import and cross-checked, so a transcribed-constant error cannot survive.
- Field tower: Fp2 = Fp[u]/(u²+1); Fp12 is represented directly in the
  w-basis (coefficients c0..c5 ∈ Fp2, w⁶ = ξ = u+1), which makes the sparse
  Miller-loop line multiplication and Frobenius cheap and avoids a separate
  Fp6 layer.
- Pairing: optimal ate.  Affine Miller loop over Fp2 with sparse (c0,c2,c3)
  line evaluation; final exponentiation = easy part, then the BLS12 hard part
  via the (x−1)²·(x+p)·(x²+p²−1)+3 multiple (a fixed 3rd-power of the
  canonical pairing, which preserves bilinearity and non-degeneracy — all
  callers only compare pairing products).
- ``pairing_check([(P,Q),...])`` shares one Miller product and one final
  exponentiation across all pairs — the batch-verification trick a future
  on-device verifier can reuse.

Representation conventions: Fp = int; Fp2 = (int, int); Fp12 = 6-tuple of
Fp2; curve points are Jacobian triples; G1 over Fp, G2 over Fp2.  Infinity is
``None``.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# Parameters (derived from the BLS parameter x)
# --------------------------------------------------------------------------

X = -0xD201000000010000  # BLS12-381 parameter (negative, low Hamming weight)

_x = X
R = _x**4 - _x**2 + 1  # subgroup order r (255 bits)
P = (_x - 1) ** 2 * R // 3 + _x  # base field prime (381 bits)
H1 = (_x - 1) ** 2 // 3  # G1 cofactor
H2 = (_x**8 - 4 * _x**7 + 5 * _x**6 - 4 * _x**4 + 6 * _x**3 - 4 * _x**2 - 4 * _x + 13) // 9  # G2 cofactor

assert P % 6 == 1 and P % 4 == 3
assert (P**4 - P**2 + 1) % R == 0  # r | Φ12(p): pairing lands in order-r group

B1 = 4  # E:  y² = x³ + 4
XI = (1, 1)  # ξ = u + 1;  E': y² = x³ + 4ξ (M-twist)

# Standard generators (checked on-curve and of order r in tests).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
    1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
    (1, 0),
)

# GLV endomorphism on G1: φ(x, y) = (β·x, y) with β a primitive cube root
# of unity in Fp acts as multiplication by λ = x²−1 on the r-order subgroup
# (λ² + λ + 1 = x⁴ − x² + 1 ≡ 0 mod r).  Any full-range scalar splits as
# s = a + b·λ with a = s mod λ, b = s // λ, both POSITIVE and < 2^128 —
# the regime the device's lazy ladder is sound in (ops/fp381.py).  β is
# derived, not transcribed: the cube root (g^((p−1)/3)) whose φ matches
# λ·G1_GEN is selected at import.
LAMBDA_G1 = _x**2 - 1
assert (LAMBDA_G1**2 + LAMBDA_G1 + 1) % R == 0
assert 0 < LAMBDA_G1 < 1 << 128 and (R - 1) // LAMBDA_G1 < 1 << 128


def _derive_beta() -> int:
    for g in range(2, 100):
        b = pow(g, (P - 1) // 3, P)
        if b != 1:
            break
    x, y, _ = G1_GEN
    for cand in (b, b * b % P):
        # φ(G) = (βx, y) must equal λ·G
        lam = g1_mul(G1_GEN, LAMBDA_G1)
        if g1_eq((cand * x % P, y, 1), lam):
            return cand
    raise AssertionError("no cube root matches the G1 endomorphism")


BETA_G1: Optional[int] = None  # filled lazily (needs g1_mul below)


def glv_beta() -> int:
    global BETA_G1
    if BETA_G1 is None:
        BETA_G1 = _derive_beta()
    return BETA_G1


def g1_endo(pt):
    """φ(P) = λ·P via one field multiplication (Jacobian: scale X by β)."""
    if pt is None:
        return None
    b = glv_beta()
    return (pt[0] * b % P, pt[1], pt[2])


# --------------------------------------------------------------------------
# Fp
# --------------------------------------------------------------------------


def fp_inv(a: int) -> int:
    return pow(a, -1, P)


def fp_sqrt(a: int) -> Optional[int]:
    """Square root in Fp (p ≡ 3 mod 4), or None."""
    r_ = pow(a, (P + 1) // 4, P)
    return r_ if r_ * r_ % P == a % P else None


# --------------------------------------------------------------------------
# Fp2 = Fp[u]/(u²+1), elements (a, b) = a + b·u
# --------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fp2_mul(a, b):
    # Karatsuba: (a0+a1u)(b0+b1u) = a0b0 − a1b1 + ((a0+a1)(b0+b1) − a0b0 − a1b1)u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fp2_sqr(a):
    # (a0+a1u)² = (a0+a1)(a0−a1) + 2a0a1·u
    t0 = (a[0] + a[1]) * (a[0] - a[1])
    t1 = 2 * a[0] * a[1]
    return (t0 % P, t1 % P)


def fp2_scal(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    return (a[0], -a[1] % P)


def fp2_inv(a):
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = pow(norm, -1, P)
    return (a[0] * ninv % P, -a[1] * ninv % P)


def fp2_pow(a, e: int):
    result = FP2_ONE
    base = a
    while e:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_sqrt(a):
    """Square root in Fp2 via the complex method (p ≡ 3 mod 4), or None."""
    if a == FP2_ZERO:
        return FP2_ZERO
    a0, a1 = a
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # a0 is a non-residue: sqrt = t·u with t² = −a0
        t = fp_sqrt(-a0 % P)
        return None if t is None else (0, t)
    n = (a0 * a0 + a1 * a1) % P
    s = fp_sqrt(n)
    if s is None:
        return None
    # α² = (a0 + s)/2 (try both roots of the norm)
    for sgn in (s, -s % P):
        half = (a0 + sgn) * pow(2, -1, P) % P
        alpha = fp_sqrt(half)
        if alpha is None or alpha == 0:
            continue
        beta = a1 * pow(2 * alpha, -1, P) % P
        cand = (alpha, beta)
        if fp2_sqr(cand) == a:
            return cand
    return None


# --------------------------------------------------------------------------
# Fp12 in the w-basis: (c0..c5), ci ∈ Fp2, w⁶ = ξ
# --------------------------------------------------------------------------

FP12_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO, FP2_ZERO, FP2_ZERO, FP2_ZERO)


def fp12_mul(a, b):
    # Schoolbook polynomial mult mod (w⁶ − ξ): 36 Fp2 muls.
    acc = [FP2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == FP2_ZERO:
            continue
        for j in range(6):
            if b[j] == FP2_ZERO:
                continue
            acc[i + j] = fp2_add(acc[i + j], fp2_mul(ai, b[j]))
    out = list(acc[:6])
    for k in range(6, 11):
        if acc[k] != FP2_ZERO:
            out[k - 6] = fp2_add(out[k - 6], fp2_mul(acc[k], XI))
    return tuple(out)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """Conjugation = f^(p⁶): negates odd-w coefficients."""
    return (a[0], fp2_neg(a[1]), a[2], fp2_neg(a[3]), a[4], fp2_neg(a[5]))


def fp12_inv(a):
    """Inverse via the tower: split into even/odd parts A + B·w over
    Fp6 = Fp2[v]/(v³−ξ) with v = w²: (A + Bw)⁻¹ = (A − Bw)/(A² − B²v)."""
    A = (a[0], a[2], a[4])  # Fp6 coeffs in v
    B = (a[1], a[3], a[5])
    A2 = _fp6_sqr(A)
    B2 = _fp6_sqr(B)
    # A² − v·B²  (v·(b0,b1,b2) = (ξ·b2, b0, b1))
    vB2 = (fp2_mul(B2[2], XI), B2[0], B2[1])
    denom = _fp6_sub(A2, vB2)
    dinv = _fp6_inv(denom)
    num_even = _fp6_mul(A, dinv)
    num_odd = _fp6_neg(_fp6_mul(B, dinv))
    return (
        num_even[0], num_odd[0], num_even[1], num_odd[1], num_even[2], num_odd[2],
    )


def _fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def _fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def _fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def _fp6_mul(a, b):
    t = [FP2_ZERO] * 5
    for i in range(3):
        if a[i] == FP2_ZERO:
            continue
        for j in range(3):
            t[i + j] = fp2_add(t[i + j], fp2_mul(a[i], b[j]))
    return (
        fp2_add(t[0], fp2_mul(t[3], XI)),
        fp2_add(t[1], fp2_mul(t[4], XI)),
        t[2],
    )


def _fp6_sqr(a):
    return _fp6_mul(a, a)


def _fp6_inv(a):
    """Itoh–Tsujii style via adjugate over Fp2."""
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sqr(a0), fp2_mul(XI, fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul(XI, fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    # norm = a0c0 + ξ(a2c1 + a1c2)
    norm = fp2_add(
        fp2_mul(a0, c0),
        fp2_mul(XI, fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))),
    )
    ninv = fp2_inv(norm)
    return (fp2_mul(c0, ninv), fp2_mul(c1, ninv), fp2_mul(c2, ninv))


# Frobenius: (Σ ci wⁱ)^p = Σ conj(ci)·γi·wⁱ with γi = ξ^{i(p−1)/6}.
_FROB_GAMMA = tuple(fp2_pow(XI, i * (P - 1) // 6) for i in range(6))


def fp12_frobenius(a, power: int = 1):
    out = a
    for _ in range(power):
        out = tuple(
            fp2_mul(fp2_conj(out[i]), _FROB_GAMMA[i]) for i in range(6)
        )
    return out


def fp12_pow(a, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


def _cyc_pow_x(a):
    """a^|x| in the cyclotomic subgroup (conjugate = inverse there)."""
    return fp12_pow(a, -X)  # −X = |x| > 0


# --------------------------------------------------------------------------
# Curves (Jacobian coordinates; None = infinity)
# --------------------------------------------------------------------------
# G1: tuples of ints (X, Y, Z); G2: tuples of Fp2.


def _jac_double(pt, sqr, mul, add, sub, scal):
    if pt is None:
        return None
    x, y, z = pt
    a = sqr(x)
    b = sqr(y)
    c = sqr(b)
    d = sub(sqr(add(x, b)), add(a, c))
    d = add(d, d)
    e = add(add(a, a), a)
    f = sqr(e)
    x3 = sub(f, add(d, d))
    y3 = sub(mul(e, sub(d, x3)), scal(c, 8))
    z3 = mul(add(y, y), z)
    return (x3, y3, z3)


def _jac_add(p1, p2, sqr, mul, add, sub, scal, zero_check, double):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1 = sqr(z1)
    z2z2 = sqr(z2)
    u1 = mul(x1, z2z2)
    u2 = mul(x2, z1z1)
    s1 = mul(mul(y1, z2), z2z2)
    s2 = mul(mul(y2, z1), z1z1)
    if zero_check(sub(u1, u2)):
        if zero_check(sub(s1, s2)):
            return double(p1)
        return None  # inverses
    h = sub(u2, u1)
    i = sqr(add(h, h))
    j = mul(h, i)
    r = sub(s2, s1)
    r = add(r, r)
    v = mul(u1, i)
    x3 = sub(sub(sqr(r), j), add(v, v))
    y3 = sub(mul(r, sub(v, x3)), scal(mul(s1, j), 2))
    z3 = mul(scal(mul(z1, z2), 2), h)
    return (x3, y3, z3)


# --- G1 (ints) ---


def _isqr(a):
    return a * a % P


def _imul(a, b):
    return a * b % P


def _iadd(a, b):
    return (a + b) % P


def _isub(a, b):
    return (a - b) % P


def _iscal(a, k):
    return a * k % P


def g1_double(pt):
    return _jac_double(pt, _isqr, _imul, _iadd, _isub, _iscal)


def g1_add(p1, p2):
    return _jac_add(
        p1, p2, _isqr, _imul, _iadd, _isub, _iscal, lambda t: t % P == 0, g1_double
    )


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], -pt[1] % P, pt[2])


# Scalars at or below this bit length skip the native oracle: a native
# scalar-mul pays a fixed serialize/ladder/deserialize cost (~130 µs even
# for k=3), while a Python double-and-add costs ~5 µs per group op — so a
# 12-bit scalar (≤ 18 ops) is an order of magnitude cheaper in Python.
# DKG evaluation points are node indices (x = i+1 ≤ N), which is what
# makes Horner-form commitment evaluation fast (see tc.BivarCommitment).
SMALL_SCALAR_BITS = 12


def g1_mul(pt, k: int):
    k %= R
    nat = _native()
    if 0 < k < (1 << SMALL_SCALAR_BITS) or nat is None or pt is None:
        result = None
        add = pt
        while k:
            if k & 1:
                result = g1_add(result, add)
            add = g1_double(add)
            k >>= 1
        return result
    return _g1_from_bytes_trusted(nat.bls_g1_mul(g1_to_bytes(pt), k))


def g1_affine(pt):
    if pt is None:
        return None
    x, y, z = pt
    zi = fp_inv(z)
    zi2 = zi * zi % P
    return (x * zi2 % P, y * zi2 % P * zi % P, 1)


def g1_eq(p1, p2) -> bool:
    if p1 is None or p2 is None:
        return p1 is p2 or (p1 is None and p2 is None)
    # cross-multiply to compare without inversion
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1, z2z2 = z1 * z1 % P, z2 * z2 % P
    if (x1 * z2z2 - x2 * z1z1) % P:
        return False
    return (y1 * z2z2 % P * z2 - y2 * z1z1 % P * z1) % P == 0


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y, z = g1_affine(pt)
    return (y * y - x * x * x - B1) % P == 0


# --- G2 (Fp2) ---


def _f2zero(t):
    return t == FP2_ZERO or (t[0] % P == 0 and t[1] % P == 0)


def g2_double(pt):
    return _jac_double(pt, fp2_sqr, fp2_mul, fp2_add, fp2_sub, fp2_scal)


def g2_add(p1, p2):
    return _jac_add(
        p1, p2, fp2_sqr, fp2_mul, fp2_add, fp2_sub, fp2_scal, _f2zero, g2_double
    )


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], fp2_neg(pt[1]), pt[2])


def g2_mul(pt, k: int, mod_r: bool = True):
    if mod_r:
        k %= R
        nat = _native()
        if nat is not None and pt is not None:
            return _g2_from_bytes_trusted(nat.bls_g2_mul(g2_to_bytes(pt), k))
    result = None
    add = pt
    while k:
        if k & 1:
            result = g2_add(result, add)
        add = g2_double(add)
        k >>= 1
    return result


def g2_affine(pt):
    if pt is None:
        return None
    x, y, z = pt
    zi = fp2_inv(z)
    zi2 = fp2_sqr(zi)
    return (fp2_mul(x, zi2), fp2_mul(fp2_mul(y, zi2), zi), FP2_ONE)


def g2_eq(p1, p2) -> bool:
    if p1 is None or p2 is None:
        return p1 is None and p2 is None
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1, z2z2 = fp2_sqr(z1), fp2_sqr(z2)
    if not _f2zero(fp2_sub(fp2_mul(x1, z2z2), fp2_mul(x2, z1z1))):
        return False
    return _f2zero(
        fp2_sub(
            fp2_mul(fp2_mul(y1, z2z2), z2), fp2_mul(fp2_mul(y2, z1z1), z1)
        )
    )


_B2 = fp2_scal(XI, B1)  # 4(u+1)


# --- ψ endomorphism on E'(Fp2) (untwist–Frobenius–twist) -------------------
#
# For the M-twist E': y² = x³ + 4ξ (Φ: (x, y) ↦ (x/ξ^{1/3}, y/ξ^{1/2}) into
# E over Fp12), ψ = Φ⁻¹ ∘ π_p ∘ Φ is an endomorphism of E' defined over Fp2:
# ψ(x, y) = (c_x·x̄, c_y·ȳ) with c_x = ξ^{(1−p)/3}, c_y = ξ^{(1−p)/2} (bars =
# Fp2 conjugation).  On the r-order subgroup G2 it acts as multiplication by
# p ≡ X (mod r) — the basis of the fast cofactor clearing below and the GLS
# scalar decomposition the native oracle uses.  Constants are derived, not
# transcribed, and self-checked against the eigenvalue on the generator.

_PSI: Optional[tuple] = None


def _psi_consts() -> tuple:
    global _PSI
    if _PSI is None:
        cx = fp2_inv(fp2_pow(XI, (P - 1) // 3))
        cy = fp2_inv(fp2_pow(XI, (P - 1) // 2))
        # self-check: ψ(G2_GEN) = [X mod r]·G2_GEN (pure-Python ladder — the
        # native oracle derives its constants from this module, so the check
        # must not route through it)
        g = G2_GEN
        cand = (
            fp2_mul(cx, fp2_conj(g[0])),
            fp2_mul(cy, fp2_conj(g[1])),
            fp2_conj(g[2]),
        )
        k = X % R
        acc, add = None, g
        while k:
            if k & 1:
                acc = g2_add(acc, add)
            add = g2_double(add)
            k >>= 1
        assert g2_eq(cand, acc), "psi constants failed the eigenvalue check"
        _PSI = (cx, cy)
    return _PSI


def g2_psi(pt):
    """ψ(P) — one conjugation + two Fp2 muls (Jacobian coordinates)."""
    if pt is None:
        return None
    cx, cy = _psi_consts()
    return (
        fp2_mul(cx, fp2_conj(pt[0])),
        fp2_mul(cy, fp2_conj(pt[1])),
        fp2_conj(pt[2]),
    )


# --- ψ² — the GLS split the device G2 ladders use --------------------------
#
# The two conjugations in ψ∘ψ cancel, so ψ²(x, y, z) = (n_x·x, n_y·y, z)
# with n_x = c_x·c̄_x, n_y = c_y·c̄_y ∈ Fp — a pure coordinate scaling, the
# exact G2 analog of GLV's φ(x, y) = (β·x, y) on G1.  On the r-order
# subgroup ψ² acts as multiplication by p² ≡ X² (mod r); X² ≈ 2^127.7, so a
# full-range scalar splits as s = a + b·X² with a = s mod X², b = s ÷ X² —
# both POSITIVE and < 2^128, the regime the device's lazy ladder is sound
# in (ops/fp381.py), mirroring LAMBDA_G1.  Constants are derived, not
# transcribed, and self-checked against the eigenvalue on the generator.

LAMBDA_G2 = X * X  # ψ² eigenvalue on G2 (= LAMBDA_G1 + 1; no mod needed)
assert 0 < LAMBDA_G2 < 1 << 128 and (R - 1) // LAMBDA_G2 < 1 << 128

_PSI2: Optional[tuple] = None


def _psi2_consts() -> tuple:
    global _PSI2
    if _PSI2 is None:
        cx, cy = _psi_consts()
        nx = fp2_mul(cx, fp2_conj(cx))
        ny = fp2_mul(cy, fp2_conj(cy))
        assert nx[1] == 0 and ny[1] == 0, "ψ² scalings must lie in Fp"
        # eigenvalue self-check on the generator (pure-Python ladder, same
        # reasoning as _psi_consts: the native oracle derives its constants
        # from this module and must not be in the loop that validates them)
        g = G2_GEN
        cand = (fp2_scal(g[0], nx[0]), fp2_scal(g[1], ny[0]), g[2])
        k = LAMBDA_G2
        acc, add = None, g
        while k:
            if k & 1:
                acc = g2_add(acc, add)
            add = g2_double(add)
            k >>= 1
        assert g2_eq(cand, acc), "ψ² constants failed the eigenvalue check"
        _PSI2 = (nx[0], ny[0])
    return _PSI2


def g2_psi2(pt):
    """ψ²(P) = [X²]·P via two Fp2-by-Fp coordinate scalings (Jacobian)."""
    if pt is None:
        return None
    nx, ny = _psi2_consts()
    return (fp2_scal(pt[0], nx), fp2_scal(pt[1], ny), pt[2])


def g2_in_subgroup(pt) -> bool:
    """Eigenvalue subgroup test: ψ(P) == [x]P ⟺ P ∈ G2 (for on-curve P).

    Soundness: ψ's characteristic equation ψ²−[t]ψ+[p] = 0 (t = x+1) turns
    ψ(P) = [x]P into [p−x]P = ∞ with p−x = h₁·r, so ord(P) divides
    gcd(h₁·r, h₂·r) = r·gcd(h₁, h₂) = r (gcd asserted in tests).  One ψ +
    one 64-bit ladder replaces the [r−1] full-width check.
    """
    if pt is None:
        return True
    nat = _native()
    if nat is not None:
        return nat.bls_g2_in_subgroup(g2_to_bytes(pt))
    return g2_eq(g2_psi(pt), g2_neg(g2_mul(pt, -X, mod_r=False)))


def g2_clear_cofactor(pt):
    """Map any E'(Fp2) point into the r-order subgroup G2.

    Budroni–Pintore ψ-based clearing (the method RFC 9380 §8.8.2 uses for
    BLS12-381 G2): [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P), computed with two
    64-bit ladders ([|x|]P, then [|x|] of that) instead of the naive
    512-bit multiplication by the full cofactor h₂ — ~8× fewer point
    operations.  The image is [h_eff]P for the effective cofactor
    h_eff ≡ 3·h₂·(…unit mod r), so it differs pointwise from [h₂]P but
    serves the same role; the scheme is self-consistent (tc.py docstring).
    """
    if pt is None:
        return None
    xa = -X
    a = g2_neg(g2_mul(pt, xa, mod_r=False))       # [x]P   (x < 0)
    b = g2_neg(g2_mul(a, xa, mod_r=False))        # [x²]P
    t1 = g2_add(g2_add(b, g2_neg(a)), g2_neg(pt))  # [x²−x−1]P
    t2 = g2_psi(g2_add(a, g2_neg(pt)))             # [x−1]ψ(P)
    t3 = g2_psi(g2_psi(g2_double(pt)))             # ψ²([2]P)
    return g2_add(g2_add(t1, t2), t3)


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y, _ = g2_affine(pt)
    return fp2_sub(fp2_sqr(y), fp2_add(fp2_mul(fp2_sqr(x), x), _B2)) == FP2_ZERO


# --------------------------------------------------------------------------
# Pairing (optimal ate)
# --------------------------------------------------------------------------


def _line_sparse(c0, c2, c3):
    """Fp12 element c0 + c2·w² + c3·w³ (the sparse line)."""
    return (c0, FP2_ZERO, c2, c3, FP2_ZERO, FP2_ZERO)


def _miller_loop(pairs) -> tuple:
    """Π miller(P_i, Q_i) for affine G1 points P_i and affine G2 points Q_i.

    Affine Miller loop: R starts at Q; per bit of |x| (MSB-1 down): square f,
    multiply the doubling line, double R; on set bits also the addition line.
    The line through untwisted points, scaled by w³ (an Fp2-subfield constant
    that final exponentiation kills), is
    ``(λ·x_R − y_R) − λ·x_P·w² + y_P·w³`` with λ ∈ Fp2 the twist-curve slope.
    """
    work = []
    for (Pp, Qp) in pairs:
        pa = g1_affine(Pp)
        qa = g2_affine(Qp)
        if pa is None or qa is None:
            continue
        work.append((pa, qa))
    f = FP12_ONE
    if not work:
        return f
    xs = -X  # |x|
    bits = bin(xs)[3:]  # skip MSB
    Rs = [q for (_, q) in work]
    for b in bits:
        f = fp12_sqr(f)
        for i, ((xp, yp, _), (xq, yq, _)) in enumerate(work):
            Rx, Ry, _ = Rs[i]
            # doubling line at R
            lam = fp2_mul(
                fp2_scal(fp2_sqr(Rx), 3), fp2_inv(fp2_scal(Ry, 2))
            )
            c0 = fp2_sub(fp2_mul(lam, Rx), Ry)
            c2 = fp2_neg(fp2_scal(lam, xp))
            c3 = (yp % P, 0)
            f = fp12_mul(f, _line_sparse(c0, c2, c3))
            # R = 2R
            x3 = fp2_sub(fp2_sqr(lam), fp2_scal(Rx, 2))
            y3 = fp2_sub(fp2_mul(lam, fp2_sub(Rx, x3)), Ry)
            Rs[i] = (x3, y3, FP2_ONE)
        if b == "1":
            for i, ((xp, yp, _), (xq, yq, _)) in enumerate(work):
                Rx, Ry, _ = Rs[i]
                if _f2zero(fp2_sub(Rx, xq)):
                    # R == ±Q; adding Q to R=−Q gives vertical line (killed);
                    # R == Q would double — can't happen mid-loop for r-order Q.
                    Rs[i] = g2_affine(g2_add((Rx, Ry, FP2_ONE), (xq, yq, FP2_ONE)))
                    continue
                lam = fp2_mul(fp2_sub(Ry, yq), fp2_inv(fp2_sub(Rx, xq)))
                c0 = fp2_sub(fp2_mul(lam, xq), yq)
                c2 = fp2_neg(fp2_scal(lam, xp))
                c3 = (yp % P, 0)
                f = fp12_mul(f, _line_sparse(c0, c2, c3))
                x3 = fp2_sub(fp2_sub(fp2_sqr(lam), Rx), xq)
                y3 = fp2_sub(fp2_mul(lam, fp2_sub(Rx, x3)), Ry)
                Rs[i] = (x3, y3, FP2_ONE)
    # x < 0: conjugate (f ← f^(p⁶)) — standard sign fix for BLS12.
    return fp12_conj(f)


def _final_exponentiation(f):
    """f^(3·(p¹²−1)/r) — a fixed cube of the canonical pairing.

    Easy part: f ← f^((p⁶−1)(p²+1)).  Hard part uses
    3·(p⁴−p²+1)/r = (x−1)²·(x+p)·(x²+p²−1) + 3.
    """
    # easy
    f = fp12_mul(fp12_conj(f), fp12_inv(f))  # f^(p⁶−1)
    f = fp12_mul(fp12_frobenius(f, 2), f)  # ^(p²+1)
    # hard (in the cyclotomic subgroup now: inverse = conjugate)
    xm1 = -X + 1  # |x−1| = |x|+1 since x<0; m^(x−1) = conj(m^|x−1|)
    t = fp12_conj(fp12_pow(f, xm1))
    t = fp12_conj(fp12_pow(t, xm1))  # t = f^((x−1)²)  (positive exponent)
    s = fp12_mul(fp12_conj(fp12_pow(t, -X)), fp12_frobenius(t, 1))  # t^(x+p)
    u = fp12_mul(
        fp12_pow(s, X * X),  # positive: x² > 0
        fp12_mul(fp12_frobenius(s, 2), fp12_conj(s)),
    )  # s^(x²+p²−1)
    return fp12_mul(u, fp12_pow(f, 3))


def pairing(p1, q2):
    """e(P, Q)³ for P ∈ G1, Q ∈ G2 (fixed cube of the ate pairing)."""
    if p1 is None or q2 is None:
        return FP12_ONE
    return _final_exponentiation(_miller_loop([(p1, q2)]))


# --------------------------------------------------------------------------
# Native (C++) fast path — byte-parity-proven oracle for the hot operations
# --------------------------------------------------------------------------
# The C++ oracle (native/bls381.cpp) implements the same algorithms with
# constants generated from this module; tests/test_native_bls.py asserts
# byte-exact parity.  The pure-Python path remains the ground truth and is
# forced with HBBFT_PURE_PYTHON=1 (parity/unit tests do this).

_NATIVE = None
_NATIVE_TRIED = False


def _native():
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        import os

        if not os.environ.get("HBBFT_PURE_PYTHON"):
            try:
                from hbbft_tpu.native import get_oracle

                _NATIVE = get_oracle()
            except Exception as exc:
                import warnings

                warnings.warn(
                    "native BLS oracle unavailable — falling back to the "
                    f"(much slower) pure-Python path: {exc!r}"
                )
                _NATIVE = None
    return _NATIVE


class pure_python:
    """Context manager forcing the pure-Python path (parity tests use this
    so both sides of a native-vs-host assertion are independent)."""

    def __enter__(self):
        global _NATIVE, _NATIVE_TRIED
        self._saved = (_NATIVE, _NATIVE_TRIED)
        _NATIVE, _NATIVE_TRIED = None, True
        return self

    def __exit__(self, *exc):
        global _NATIVE, _NATIVE_TRIED
        _NATIVE, _NATIVE_TRIED = self._saved
        return False


def pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """True iff Π e(P_i, Q_i) == 1 — one shared Miller product + final exp.

    This is how all signature/share verifications are phrased:
    ``e(g1, sig) == e(pk, H)`` ⟺ ``pairing_check([(−g1, sig), (pk, H)])``.
    """
    live = [(p, q) for (p, q) in pairs if p is not None and q is not None]
    nat = _native()
    if nat is not None:
        return nat.bls_pairing_check(
            [(g1_to_bytes(p), g2_to_bytes(q)) for p, q in live]
        )
    f = _miller_loop(live)
    return _final_exponentiation(f) == FP12_ONE


# --------------------------------------------------------------------------
# Hash to G2 (try-and-increment; random-oracle into the r-order subgroup)
# --------------------------------------------------------------------------


def _hash_fp2(data: bytes, ctr: int) -> tuple:
    h0 = hashlib.sha3_256(b"HBBFT-H2G-c0" + ctr.to_bytes(4, "big") + data).digest()
    h1 = hashlib.sha3_256(b"HBBFT-H2G-c1" + ctr.to_bytes(4, "big") + data).digest()
    h2 = hashlib.sha3_256(b"HBBFT-H2G-c2" + ctr.to_bytes(4, "big") + data).digest()
    h3 = hashlib.sha3_256(b"HBBFT-H2G-c3" + ctr.to_bytes(4, "big") + data).digest()
    a = int.from_bytes(h0 + h1, "big") % P
    b = int.from_bytes(h2 + h3, "big") % P
    return (a, b)


def hash_g2(data: bytes):
    """Hash arbitrary bytes to a point of order r in G2.

    Try-and-increment: hash to an x-candidate in Fp2, solve for y, clear the
    cofactor.  (The reference's ``threshold_crypto::hash_g2`` fills the same
    role; bit-compatibility with it is not required — only internal
    consistency, as with all our crypto.)
    """
    nat = _native()
    if nat is not None:
        return _g2_from_bytes_trusted(nat.bls_hash_g2(bytes(data)))
    ctr = 0
    while True:
        x = _hash_fp2(data, ctr)
        rhs = fp2_add(fp2_mul(fp2_sqr(x), x), _B2)
        y = fp2_sqrt(rhs)
        if y is not None and y != FP2_ZERO:
            # canonical sign from the hash, for determinism
            if int.from_bytes(
                hashlib.sha3_256(b"HBBFT-H2G-sign" + ctr.to_bytes(4, "big") + data).digest(),
                "big",
            ) & 1:
                y = fp2_neg(y)
            pt = (x, y, FP2_ONE)
            pt = g2_clear_cofactor(pt)  # ψ-based clearing → r-order subgroup
            if pt is not None:
                return pt
        ctr += 1


def hash_g1(data: bytes):
    """Hash to G1 (same approach; used for plain per-node signatures)."""
    nat = _native()
    if nat is not None:
        return _g1_from_bytes_trusted(nat.bls_hash_g1(bytes(data)))
    ctr = 0
    while True:
        h0 = hashlib.sha3_256(b"HBBFT-H1G-0" + ctr.to_bytes(4, "big") + data).digest()
        h1 = hashlib.sha3_256(b"HBBFT-H1G-1" + ctr.to_bytes(4, "big") + data).digest()
        x = int.from_bytes(h0 + h1, "big") % P
        rhs = (x * x % P * x + B1) % P
        y = fp_sqrt(rhs)
        if y is not None and y != 0:
            if int.from_bytes(
                hashlib.sha3_256(b"HBBFT-H1G-s" + ctr.to_bytes(4, "big") + data).digest(),
                "big",
            ) & 1:
                y = -y % P
            pt = (x, y, 1)
            # effective cofactor 1−x (64-bit) in place of the 125-bit h₁ —
            # the standard G1 clearing (RFC 9380 §8.8.1's h_eff); ~2× fewer
            # ladder steps, image still the r-order subgroup (tested)
            pt = _g1_mul_nat(pt, 1 - X)
            if pt is not None:
                return pt
        ctr += 1


def _g1_mul_nat(pt, k: int):
    """Scalar mult by a natural number (no mod-r reduction; cofactor use)."""
    result = None
    add = pt
    while k:
        if k & 1:
            result = g1_add(result, add)
        add = g1_double(add)
        k >>= 1
    return result


# --------------------------------------------------------------------------
# Serialization (affine, uncompressed-with-flags; self-consistent format)
# --------------------------------------------------------------------------


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x40" + bytes(96)  # infinity flag
    x, y, _ = g1_affine(pt)
    return b"\x00" + x.to_bytes(48, "big") + y.to_bytes(48, "big")


def _g1_from_bytes_trusted(data: bytes):
    """Deserialize WITHOUT curve/subgroup checks — only for values produced
    by the byte-parity-proven native oracle."""
    if data[0] == 0x40:
        return None
    return (
        int.from_bytes(data[1:49], "big"),
        int.from_bytes(data[49:97], "big"),
        1,
    )


def _g2_from_bytes_trusted(data: bytes):
    if data[0] == 0x40:
        return None
    vals = [int.from_bytes(data[1 + i * 48 : 49 + i * 48], "big") for i in range(4)]
    return ((vals[0], vals[1]), (vals[2], vals[3]), FP2_ONE)


def g1_in_subgroup(pt) -> bool:
    """Eigenvalue subgroup test: φ(P) == [λ]P ⟺ P ∈ G1 (for on-curve P).

    Soundness: φ satisfies φ²+φ+1 = 0 in End(E), so φ(P) = [λ]P forces
    [λ²+λ+1]P = [r·k]P = ∞, and ord(P) | gcd(h₁·r, r·k) = r·gcd(h₁, k) = r
    (gcd asserted in tests).  A 127-bit ladder replaces the [r−1] check.
    """
    if pt is None:
        return True
    nat = _native()
    if nat is not None:
        return nat.bls_g1_in_subgroup(g1_to_bytes(pt))
    return g1_eq(g1_endo(pt), g1_mul(pt, LAMBDA_G1))


def g1_from_bytes(data: bytes):
    if data[0] == 0x40:
        # strict: the only valid infinity encoding is the flag followed by
        # 96 zero bytes — a consensus-validated wire format must not admit
        # malleable (or truncated) encodings of the identity (the native
        # g1_read_checked reads the same fixed 97-byte frame;
        # tests/test_crypto.py sweeps the accept sets)
        if len(data) < 97 or any(data[1:97]):
            raise ValueError("nonzero bytes after the G1 infinity flag")
        return None
    if data[0] != 0:
        # strict decode: the only defined flags are 0x00 and 0x40 (the
        # native g1_read enforces the same)
        raise ValueError("invalid G1 flag byte")
    x = int.from_bytes(data[1:49], "big")
    y = int.from_bytes(data[49:97], "big")
    if x >= P or y >= P:
        raise ValueError("non-canonical G1 coordinates")
    pt = (x, y, 1)
    if not g1_is_on_curve(pt):
        raise ValueError("invalid G1 point")
    # Subgroup check: on-curve is not enough — cofactor-torsion components
    # survive pairing-based verification (killed by the final exponentiation)
    # but corrupt Lagrange combination of "verified" shares.
    if not g1_in_subgroup(pt):
        raise ValueError("G1 point not in the r-order subgroup")
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x40" + bytes(192)
    (x0, x1), (y0, y1), _ = g2_affine(pt)
    return (
        b"\x00"
        + x0.to_bytes(48, "big")
        + x1.to_bytes(48, "big")
        + y0.to_bytes(48, "big")
        + y1.to_bytes(48, "big")
    )


def g2_from_bytes(data: bytes):
    if data[0] == 0x40:
        # strict infinity: the full 193-byte frame, flag + zeros only
        if len(data) < 193 or any(data[1:193]):
            raise ValueError("nonzero bytes after the G2 infinity flag")
        return None
    if data[0] != 0:
        raise ValueError("invalid G2 flag byte")
    vals = [int.from_bytes(data[1 + i * 48 : 49 + i * 48], "big") for i in range(4)]
    if any(v >= P for v in vals):
        raise ValueError("non-canonical G2 coordinates")
    pt = ((vals[0], vals[1]), (vals[2], vals[3]), FP2_ONE)
    if not g2_is_on_curve(pt):
        raise ValueError("invalid G2 point")
    if not g2_in_subgroup(pt):
        raise ValueError("G2 point not in the r-order subgroup")
    return pt

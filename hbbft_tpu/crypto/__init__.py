"""Threshold cryptography for the protocol stack.

The reference delegates all crypto to the external ``threshold_crypto`` crate
(BLS12-381 threshold signatures + TPKE threshold encryption over
``pairing``/``ff``; SURVEY §2.2).  This package provides:

- ``bls12_381`` — the curve: Fp/Fp2/Fp6/Fp12 tower, G1/G2, optimal ate
  pairing, hash-to-G2.  Pure-Python ints (ground truth / CPU path).
- ``tc`` — a ``threshold_crypto``-compatible API surface
  (``SecretKeySet``/``PublicKeySet``/``Poly``/``BivarPoly``/``Ciphertext``/…)
  so the protocol layer never touches curve internals.  The batched jnp
  backend slots in behind the same API (the ``backend="jax"`` provider
  boundary named by BASELINE.json's north star).
"""

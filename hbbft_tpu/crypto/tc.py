"""``threshold_crypto``-compatible threshold BLS + TPKE.

Mirrors the API surface of the ``threshold_crypto`` crate the reference links
(SURVEY §2.2): ``SecretKey``/``PublicKey`` (plain BLS), ``SecretKeySet``/
``PublicKeySet``/``SecretKeyShare``/``PublicKeyShare``/``SignatureShare``
(threshold signatures — the common coin), ``Ciphertext``/``DecryptionShare``
(threshold encryption — HoneyBadger contributions), and ``Poly``/
``BivarPoly``/``Commitment``/``BivarCommitment`` (the DKG substrate for
``SyncKeyGen``).

Scheme (self-consistent; bit-compat with the Rust crate is not required):
 - public keys in G1 (``pk = g1^sk``), signatures in G2 (``σ = H_G2(m)^sk``),
   verification ``e(g1, σ) == e(pk, H)`` via a single product-pairing check.
 - threshold keys from a degree-t polynomial f over Fr: share i is f(i+1);
   t+1 shares Lagrange-interpolate at 0 (in the exponent for combination).
 - TPKE (Baek–Zheng style, as in ``threshold_crypto::Ciphertext{U,V,W}``):
   U = g1^r, V = m ⊕ KDF(pk^r), W = H_G2(U‖V)^r; validity
   ``e(g1, W) == e(U, H)``; decryption share i is U^{x_i} verified by
   ``e(share, H) == e(pk_i, W)``; t+1 shares interpolate U^{f(0)} = pk^r.

All randomness comes from caller-supplied ``random.Random`` instances —
protocols stay deterministic from a seed, as in the reference's test design.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from hbbft_tpu.crypto import bls12_381 as c

R = c.R

# --------------------------------------------------------------------------
# Fr helpers
# --------------------------------------------------------------------------


def _lagrange_coeffs_at_zero(xs: Sequence[int]) -> List[int]:
    """λ_i(0) for interpolation points xs (distinct, nonzero mod r)."""
    coeffs = []
    for i, xi in enumerate(xs):
        num, den = 1, 1
        for j, xj in enumerate(xs):
            if i == j:
                continue
            num = num * (-xj) % R
            den = den * (xi - xj) % R
        coeffs.append(num * pow(den, -1, R) % R)
    return coeffs


def master_secret_from_shares(shares) -> int:
    """f(0) interpolated from (index, scalar) share pairs.

    The god-view fold used by the batched simulator: combining shares of a
    common base point Lagrange-in-the-exponent equals one scalar-mul by
    this master secret.  Caller passes exactly the t+1 shares it would
    hand to ``combine_signatures``/``decrypt`` (same index convention:
    evaluation points are index+1)."""
    items = sorted(shares)
    lams = _lagrange_coeffs_at_zero([i + 1 for i, _ in items])
    return sum(lam * x for (_, x), lam in zip(items, lams)) % R


def _kdf_stream(seed: bytes, length: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < length:
        out += hashlib.sha3_256(seed + ctr.to_bytes(4, "big")).digest()
        ctr += 1
    return out[:length]


def _hash_ciphertext_point(u, v: bytes):
    return c.hash_g2(b"HBBFT-TPKE" + c.g1_to_bytes(u) + v)


def tpke_encrypt_batch(
    pk: "PublicKey", msgs: Sequence[bytes], rng,
    backend: Optional[str] = None,
) -> List["Ciphertext"]:
    """Encrypt many contributions to one threshold key.

    Draws one scalar per message from ``rng`` and is byte-identical to
    sequential ``pk.encrypt(msg, rng)`` calls regardless of backend
    (tests assert it).  ``backend`` (default: env HBBFT_ENCRYPT_BACKEND,
    then "auto"):

    - ``"native"``: the WHOLE batch is one C call — GIL released
      throughout, so the epoch pipeline's encrypt-ahead thread overlaps
      with device work for real (parallel/qhb.py); per-item cost is the
      endomorphism fast paths (fixed-base U, windowed pk^r, ψ-based
      hash-to-G2, GLS W).  Falls back to per-item Python if the oracle is
      unavailable.
    - ``"device"``: the SPLIT path — 2×G1 + GLS-G2 ladders for all
      proposers as device MSM dispatches, hash-to-G2 in a native batch
      call, chunk-pipelined so the host hash overlaps the device ladders
      (:func:`hbbft_tpu.crypto.batch.batch_tpke_encrypt_device`).
    - ``"auto"``: device only where the measured roofline says it wins —
      a >1-chip mesh on a real accelerator, or no native oracle; the
      single-chip compute-bound regime stays with the 40 ns/mul host asm
      (see the roofline note in crypto/batch.py).

    This is the batched-device-encrypt lever of SURVEY §3.1's HOT encrypt
    row."""
    import os

    rs = [rng.randrange(1, R) for _ in msgs]
    if backend is None:
        backend = os.environ.get("HBBFT_ENCRYPT_BACKEND") or "auto"
    if backend not in ("auto", "native", "device"):
        raise ValueError(
            f"HBBFT_ENCRYPT_BACKEND={backend!r}: expected "
            "'auto', 'native' or 'device'"
        )
    if backend != "native":
        from hbbft_tpu.crypto import batch as _batch

        if backend == "device" or _batch.device_encrypt_worthwhile(len(msgs)):
            return _batch.batch_tpke_encrypt_device(pk.point, msgs, rs)
    nat = c._native()
    if nat is not None:
        out = nat.bls_tpke_encrypt_batch(
            pk.to_bytes(), [bytes(m) for m in msgs], rs
        )
        return [
            Ciphertext(
                c._g1_from_bytes_trusted(u), v, c._g2_from_bytes_trusted(w)
            )
            for (u, v, w) in out
        ]
    return [pk._encrypt_with_r(m, r) for m, r in zip(msgs, rs)]


# --------------------------------------------------------------------------
# Plain keys (per-node; DHB votes, SyncKeyGen row encryption)
# --------------------------------------------------------------------------


class Signature:
    """BLS signature (G2).  ``parity()`` is the common-coin bit."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def to_bytes(self) -> bytes:
        return c.g2_to_bytes(self.point)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        return cls(c.g2_from_bytes(data))

    def parity(self) -> bool:
        return bool(hashlib.sha3_256(self.to_bytes()).digest()[0] & 1)

    def __eq__(self, other):
        return isinstance(other, Signature) and c.g2_eq(self.point, other.point)

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"Signature({self.to_bytes()[:9].hex()}…)"


class SignatureShare(Signature):
    """One node's signature share (G2)."""


class PublicKey:
    """Plain BLS public key (G1)."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def to_bytes(self) -> bytes:
        return c.g1_to_bytes(self.point)

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        return cls(c.g1_from_bytes(data))

    def verify(self, sig: Signature, msg: bytes) -> bool:
        h = c.hash_g2(msg)
        return c.pairing_check(
            [(c.g1_neg(c.G1_GEN), sig.point), (self.point, h)]
        )

    def encrypt(self, msg: bytes, rng) -> "Ciphertext":
        """Hybrid encryption to this key (TPKE-shaped: (U, V, W))."""
        return self._encrypt_with_r(msg, rng.randrange(1, R))

    def _encrypt_with_r(self, msg: bytes, r: int) -> "Ciphertext":
        u = c.g1_mul(c.G1_GEN, r)
        mask = c.g1_mul(self.point, r)
        v = bytes(
            a ^ b
            for a, b in zip(
                msg, _kdf_stream(c.g1_to_bytes(mask), len(msg))
            )
        )
        w = c.g2_mul(_hash_ciphertext_point(u, v), r)
        return Ciphertext(u, v, w)

    def __eq__(self, other):
        return isinstance(other, PublicKey) and c.g1_eq(self.point, other.point)

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"PublicKey({self.to_bytes()[:9].hex()}…)"

    def __lt__(self, other):  # stable ordering for membership maps
        return self.to_bytes() < other.to_bytes()


class PublicKeyShare(PublicKey):
    """Public counterpart of a secret key share."""

    def verify_decryption_share(self, share: "DecryptionShare", ct: "Ciphertext") -> bool:
        h = _hash_ciphertext_point(ct.u, ct.v)
        return c.pairing_check(
            [(c.g1_neg(share.point), h), (self.point, ct.w)]
        )


class SecretKey:
    """Plain BLS secret key (Fr scalar)."""

    __slots__ = ("scalar",)

    def __init__(self, scalar: int):
        self.scalar = scalar % R

    @classmethod
    def random(cls, rng) -> "SecretKey":
        return cls(rng.randrange(1, R))

    @classmethod
    def from_value(cls, v: int) -> "SecretKey":
        return cls(v)

    def public_key(self) -> PublicKey:
        return PublicKey(c.g1_mul(c.G1_GEN, self.scalar))

    def sign(self, msg: bytes) -> Signature:
        return Signature(c.g2_mul(c.hash_g2(msg), self.scalar))

    def decrypt(self, ct: "Ciphertext") -> Optional[bytes]:
        if not ct.verify():
            return None
        mask = c.g1_mul(ct.u, self.scalar)
        return bytes(
            a ^ b
            for a, b in zip(
                ct.v, _kdf_stream(c.g1_to_bytes(mask), len(ct.v))
            )
        )

    def __repr__(self):
        return "SecretKey(<redacted>)"


class SecretKeyShare(SecretKey):
    """One node's share x_i = f(i+1) of the master secret f(0)."""

    def sign(self, msg: bytes) -> SignatureShare:  # type: ignore[override]
        return SignatureShare(c.g2_mul(c.hash_g2(msg), self.scalar))

    def decrypt_share(
        self, ct: "Ciphertext", check: bool = True
    ) -> Optional["DecryptionShare"]:
        """Our share U^{x_i}.  ``check=False`` skips the (pairing-priced)
        CCA validity check when the caller already verified the ciphertext."""
        if check and not ct.verify():
            return None
        return DecryptionShare(c.g1_mul(ct.u, self.scalar))

    def public_key_share(self) -> PublicKeyShare:
        return PublicKeyShare(c.g1_mul(c.G1_GEN, self.scalar))

    def __repr__(self):
        return "SecretKeyShare(<redacted>)"


class Ciphertext:
    """TPKE ciphertext (U ∈ G1, V bytes, W ∈ G2).

    Reference: ``threshold_crypto::Ciphertext`` — HoneyBadger proposes these
    and validates them before accepting a contribution
    (``src/honey_badger/epoch_state.rs``).
    """

    __slots__ = ("u", "v", "w")

    def __init__(self, u, v: bytes, w):
        self.u = u
        self.v = v
        self.w = w

    def verify(self) -> bool:
        """CCA check: e(g1, W) == e(U, H_G2(U‖V))."""
        h = _hash_ciphertext_point(self.u, self.v)
        return c.pairing_check([(c.g1_neg(self.u), h), (c.G1_GEN, self.w)])

    def to_bytes(self) -> bytes:
        return (
            c.g1_to_bytes(self.u)
            + c.g2_to_bytes(self.w)
            + len(self.v).to_bytes(4, "big")
            + self.v
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Ciphertext":
        u = c.g1_from_bytes(data[:97])
        w = c.g2_from_bytes(data[97:290])
        vlen = int.from_bytes(data[290:294], "big")
        return cls(u, data[294 : 294 + vlen], w)

    def __eq__(self, other):
        return (
            isinstance(other, Ciphertext)
            and self.v == other.v
            and c.g1_eq(self.u, other.u)
            and c.g2_eq(self.w, other.w)
        )

    def __hash__(self):
        return hash(self.to_bytes())


class DecryptionShare:
    """U^{x_i} ∈ G1.  Reference: ``threshold_crypto::DecryptionShare``."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def to_bytes(self) -> bytes:
        return c.g1_to_bytes(self.point)

    @classmethod
    def from_bytes(cls, data: bytes) -> "DecryptionShare":
        return cls(c.g1_from_bytes(data))

    def __eq__(self, other):
        return isinstance(other, DecryptionShare) and c.g1_eq(
            self.point, other.point
        )

    def __hash__(self):
        return hash(self.to_bytes())


# --------------------------------------------------------------------------
# Polynomials over Fr and their G1 commitments (DKG substrate)
# --------------------------------------------------------------------------


class Poly:
    """Univariate polynomial over Fr.  Reference: ``threshold_crypto::Poly``."""

    __slots__ = ("coeffs",)

    def __init__(self, coeffs: Sequence[int]):
        self.coeffs = [x % R for x in coeffs]
        while len(self.coeffs) > 1 and self.coeffs[-1] == 0:
            self.coeffs.pop()

    @classmethod
    def random(cls, degree: int, rng) -> "Poly":
        return cls([rng.randrange(R) for _ in range(degree + 1)])

    @classmethod
    def constant(cls, v: int) -> "Poly":
        return cls([v])

    @classmethod
    def zero(cls) -> "Poly":
        return cls([0])

    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> int:
        acc = 0
        for coef in reversed(self.coeffs):
            acc = (acc * x + coef) % R
        return acc

    def __add__(self, other: "Poly") -> "Poly":
        n = max(len(self.coeffs), len(other.coeffs))
        a = self.coeffs + [0] * (n - len(self.coeffs))
        b = other.coeffs + [0] * (n - len(other.coeffs))
        return Poly([(x + y) % R for x, y in zip(a, b)])

    def commitment(self) -> "Commitment":
        return Commitment([c.g1_mul(c.G1_GEN, coef) for coef in self.coeffs])

    @classmethod
    def interpolate(cls, points: Sequence[Tuple[int, int]]) -> "Poly":
        """Lagrange interpolation through (x, y) pairs."""
        result = [0]
        for i, (xi, yi) in enumerate(points):
            basis = [1]
            denom = 1
            for j, (xj, _) in enumerate(points):
                if i == j:
                    continue
                # basis *= (X − xj)
                nxt = [0] * (len(basis) + 1)
                for k, bc in enumerate(basis):
                    nxt[k] = (nxt[k] - bc * xj) % R
                    nxt[k + 1] = (nxt[k + 1] + bc) % R
                basis = nxt
                denom = denom * (xi - xj) % R
            scale = yi * pow(denom, -1, R) % R
            if len(result) < len(basis):
                result += [0] * (len(basis) - len(result))
            for k, bc in enumerate(basis):
                result[k] = (result[k] + bc * scale) % R
        return cls(result)


class Commitment:
    """G1 commitment to a Poly (coefficient-wise g1^c).

    Reference: ``threshold_crypto::poly::Commitment``.
    """

    __slots__ = ("points",)

    def __init__(self, points):
        self.points = list(points)

    def degree(self) -> int:
        return len(self.points) - 1

    def evaluate(self, x: int):
        """Π points[k]^{x^k} — the commitment to poly(x)."""
        if x % R == 0:  # Horner collapses to the constant term
            return self.points[0]
        acc = None
        for pt in reversed(self.points):
            acc = c.g1_add(c.g1_mul(acc, x) if acc is not None else None, pt)
        return acc

    def __add__(self, other: "Commitment") -> "Commitment":
        n = max(len(self.points), len(other.points))
        a = self.points + [None] * (n - len(self.points))
        b = other.points + [None] * (n - len(other.points))
        return Commitment([c.g1_add(x, y) for x, y in zip(a, b)])

    def to_bytes(self) -> bytes:
        return b"".join(c.g1_to_bytes(p) for p in self.points)

    def __eq__(self, other):
        return (
            isinstance(other, Commitment)
            and len(self.points) == len(other.points)
            and all(c.g1_eq(a, b) for a, b in zip(self.points, other.points))
        )

    def __hash__(self):
        return hash(self.to_bytes())


class BivarPoly:
    """Symmetric bivariate polynomial over Fr, degree t in each variable.

    Reference: ``threshold_crypto::poly::BivarPoly`` — the DKG dealer's
    object in ``SyncKeyGen``.  Symmetry (c[i][j] == c[j][i]) is what lets
    node j cross-check node i's row against its own.
    """

    __slots__ = ("degree_", "coeffs")

    def __init__(self, degree: int, coeffs):
        self.degree_ = degree
        self.coeffs = coeffs  # (t+1)×(t+1) symmetric

    @classmethod
    def random(cls, degree: int, rng) -> "BivarPoly":
        t = degree
        m = [[0] * (t + 1) for _ in range(t + 1)]
        for i in range(t + 1):
            for j in range(i, t + 1):
                v = rng.randrange(R)
                m[i][j] = v
                m[j][i] = v
        return cls(t, m)

    def degree(self) -> int:
        return self.degree_

    def evaluate(self, x: int, y: int) -> int:
        acc = 0
        xp = 1
        for i in range(self.degree_ + 1):
            yp = 1
            for j in range(self.degree_ + 1):
                acc = (acc + self.coeffs[i][j] * xp % R * yp) % R
                yp = yp * y % R
            xp = xp * x % R
        return acc

    def row(self, x: int) -> Poly:
        """The univariate poly f(x, ·)."""
        out = []
        for j in range(self.degree_ + 1):
            acc = 0
            xp = 1
            for i in range(self.degree_ + 1):
                acc = (acc + self.coeffs[i][j] * xp) % R
                xp = xp * x % R
            out.append(acc)
        return Poly(out)

    def commitment(self) -> "BivarCommitment":
        return BivarCommitment(
            self.degree_,
            [
                [c.g1_mul(c.G1_GEN, v) for v in row]
                for row in self.coeffs
            ],
        )


class BivarCommitment:
    """G1 commitment matrix to a BivarPoly.

    Reference: ``threshold_crypto::poly::BivarCommitment``.
    """

    __slots__ = ("degree_", "points")

    def __init__(self, degree: int, points):
        self.degree_ = degree
        self.points = points

    def degree(self) -> int:
        return self.degree_

    def evaluate(self, x: int, y: int):
        # Horner in both variables: every scalar-mul is by the evaluation
        # point itself, never by a full-width power — and DKG evaluation
        # points are node indices, which g1_mul's small-scalar fast path
        # turns into a handful of Python group ops each
        acc = None
        for i in reversed(range(self.degree_ + 1)):
            row_acc = None
            for j in reversed(range(self.degree_ + 1)):
                row_acc = c.g1_add(
                    c.g1_mul(row_acc, y) if row_acc is not None else None,
                    self.points[i][j],
                )
            acc = c.g1_add(
                c.g1_mul(acc, x) if acc is not None else None, row_acc
            )
        return acc

    def row(self, x: int) -> Commitment:
        if x % R == 0:  # x^i vanishes for i > 0
            return Commitment(list(self.points[0]))
        out = []
        for j in range(self.degree_ + 1):
            # Horner over i: muls are by x itself (small for node indices)
            acc = None
            for i in reversed(range(self.degree_ + 1)):
                acc = c.g1_add(
                    c.g1_mul(acc, x) if acc is not None else None,
                    self.points[i][j],
                )
            out.append(acc)
        return Commitment(out)

    def to_bytes(self) -> bytes:
        return b"".join(
            c.g1_to_bytes(p) for row in self.points for p in row
        )

    def __eq__(self, other):
        return (
            isinstance(other, BivarCommitment)
            and self.degree_ == other.degree_
            and self.to_bytes() == other.to_bytes()
        )

    def __hash__(self):
        return hash(self.to_bytes())


# --------------------------------------------------------------------------
# Threshold key sets
# --------------------------------------------------------------------------


class PublicKeySet:
    """Threshold public key: commitment to the secret polynomial.

    Reference: ``threshold_crypto::PublicKeySet``.
    """

    __slots__ = ("commitment",)

    def __init__(self, commitment: Commitment):
        self.commitment = commitment

    def threshold(self) -> int:
        return self.commitment.degree()

    def public_key(self) -> PublicKey:
        return PublicKey(self.commitment.evaluate(0))

    def public_key_share(self, i: int) -> PublicKeyShare:
        return PublicKeyShare(self.commitment.evaluate(i + 1))

    def combine_signatures(
        self, shares: Mapping[int, SignatureShare]
    ) -> Signature:
        """Lagrange interpolation in the exponent over any t+1 shares."""
        if len(shares) < self.threshold() + 1:
            raise ValueError(
                f"need {self.threshold() + 1} shares, got {len(shares)}"
            )
        items = sorted(shares.items())[: self.threshold() + 1]
        xs = [i + 1 for i, _ in items]
        lams = _lagrange_coeffs_at_zero(xs)
        acc = None
        for (i, share), lam in zip(items, lams):
            acc = c.g2_add(acc, c.g2_mul(share.point, lam))
        return Signature(acc)

    def decrypt(
        self, shares: Mapping[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        """Combine t+1 decryption shares and strip the mask."""
        if len(shares) < self.threshold() + 1:
            raise ValueError(
                f"need {self.threshold() + 1} shares, got {len(shares)}"
            )
        items = sorted(shares.items())[: self.threshold() + 1]
        xs = [i + 1 for i, _ in items]
        lams = _lagrange_coeffs_at_zero(xs)
        acc = None
        for (i, share), lam in zip(items, lams):
            acc = c.g1_add(acc, c.g1_mul(share.point, lam))
        mask = acc  # = pk^r
        return bytes(
            a ^ b
            for a, b in zip(
                ct.v, _kdf_stream(c.g1_to_bytes(mask), len(ct.v))
            )
        )

    def verify_signature(self, sig: Signature, msg: bytes) -> bool:
        return self.public_key().verify(sig, msg)

    def verify_signature_share(
        self, i: int, share: SignatureShare, msg: bytes
    ) -> bool:
        return self.public_key_share(i).verify(share, msg)

    def to_bytes(self) -> bytes:
        return self.commitment.to_bytes()

    def __eq__(self, other):
        return isinstance(other, PublicKeySet) and self.commitment == other.commitment

    def __hash__(self):
        return hash(self.commitment)


class SecretKeySet:
    """Dealer-generated threshold secret: a random degree-t polynomial.

    Reference: ``threshold_crypto::SecretKeySet``.
    """

    __slots__ = ("poly",)

    def __init__(self, poly: Poly):
        self.poly = poly

    @classmethod
    def random(cls, threshold: int, rng) -> "SecretKeySet":
        return cls(Poly.random(threshold, rng))

    def threshold(self) -> int:
        return self.poly.degree()

    def secret_key_share(self, i: int) -> SecretKeyShare:
        return SecretKeyShare(self.poly.evaluate(i + 1))

    def public_keys(self) -> PublicKeySet:
        return PublicKeySet(self.poly.commitment())

#!/usr/bin/env python
"""QueueingHoneyBadger over a simulated network — the reference's benchmark.

Mirrors ``examples/simulation.rs``: N nodes run QHB over the deterministic
in-process simulator with a synthetic hardware model (per-message CPU lag +
bandwidth charge driving a virtual clock), committing ``--txs`` random
transactions in ``--batch-size`` proposals, and prints a per-epoch timing /
throughput table.

    python examples/simulation.py --nodes 4 --txs 200 --batch-size 50 \
        --tx-size 64 --bandwidth-gbps 1.0 --cpu-lag-us 10
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import CostModel, EventLog, NetBuilder, NullAdversary


def make_cost_model(args) -> CostModel:
    return CostModel(
        bandwidth_bps=args.bandwidth_gbps * 1e9,
        cpu_lag_s=args.cpu_lag_us * 1e-6,
    )


def gen_txs(args, rng):
    return [
        bytes(rng.randrange(256) for _ in range(args.tx_size))
        for _ in range(args.txs)
    ]


def print_virtual_time(committed: int, virtual_time: float) -> None:
    print(f"virtual time {virtual_time * 1e3:.3f} ms "
          f"({committed / max(virtual_time, 1e-12):.0f} tx/s simulated)")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txs", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=50)
    ap.add_argument("--tx-size", type=int, default=64)
    ap.add_argument("--bandwidth-gbps", type=float, default=1.0)
    ap.add_argument("--cpu-lag-us", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--batched", action="store_true",
        help="run epochs on the batched array-mode pipeline (one jitted "
             "HoneyBadger epoch per round) instead of the object-mode "
             "message pump",
    )
    ap.add_argument(
        "--remove-node", type=int, metavar="ID", default=None,
        help="with --batched: vote node ID out mid-run — the ledger drains "
             "across the DKG + era rotation (the composed "
             "queueing-over-dynamic-membership stack)",
    )
    args = ap.parse_args()

    n = args.nodes
    # arg validation BEFORE the expensive BLS keygen
    if args.remove_node is not None:
        if not args.batched:
            ap.error("--remove-node requires --batched")
        if not 0 <= args.remove_node < n:
            ap.error(f"--remove-node {args.remove_node} is not a validator "
                     f"id (0..{n - 1})")
        if n < 2:
            ap.error("--remove-node needs at least 2 nodes (someone must "
                     "remain to carry the ledger)")
    rng = random.Random(args.seed)
    print(f"generating BLS keys for {n} nodes…")
    infos = NetworkInfo.generate_map(list(range(n)), rng)

    if args.remove_node is not None:
        run_batched_dynamic(args, infos, rng)
        return
    if args.batched:
        run_batched(args, infos, rng)
        return

    trace = EventLog()
    cost = make_cost_model(args)
    net = (
        NetBuilder(list(range(n)))
        .adversary(NullAdversary())
        .trace(trace)
        .cost_model(cost)
        .using_step(
            lambda nid: QueueingHoneyBadger.builder(
                DynamicHoneyBadger.builder(infos[nid], infos[nid].secret_key())
                .rng(random.Random(1000 + nid))
                .build()
            )
            .batch_size(args.batch_size)
            .rng(random.Random(2000 + nid))
            .build()
        )
    )

    txs = gen_txs(args, rng)
    for i, tx in enumerate(txs):
        net.send_input(i % n, TxInput(tx))

    committed: set = set()
    epoch_rows = []
    seen_keys: set = set()
    scanned = 0  # index into node 0's outputs — O(1) bookkeeping per crank
    t0 = time.perf_counter()
    last_vt = 0.0
    while len(committed) < len(txs):
        if net.crank() is None:
            break
        outputs = net.nodes[0].outputs
        while scanned < len(outputs):
            out = outputs[scanned]
            scanned += 1
            if not isinstance(out, QhbBatch):
                continue
            key = (out.era, out.epoch)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            batch_txs = out.all_txs()
            new_txs = [t for t in batch_txs if t not in committed]
            committed.update(batch_txs)
            epoch_rows.append(
                (
                    key,
                    len(new_txs),
                    len(committed),
                    net.virtual_time - last_vt,
                    net.virtual_time,
                )
            )
            last_vt = net.virtual_time

    wall = time.perf_counter() - t0
    print(f"\n{'era.ep':>7} {'txs':>6} {'total':>6} {'Δvt(ms)':>9} {'vt(ms)':>9}")
    for (era, ep), ntx, tot, dvt, vt in epoch_rows:
        print(f"{era:>4}.{ep:<2} {ntx:>6} {tot:>6} "
              f"{dvt * 1e3:>9.3f} {vt * 1e3:>9.3f}")
    msgs = trace.messages_by_type()
    print(f"\ncommitted {len(committed)}/{len(txs)} txs in "
          f"{len(epoch_rows)} epochs")
    print(f"virtual time {net.virtual_time * 1e3:.3f} ms "
          f"({len(committed) / max(net.virtual_time, 1e-12):.0f} tx/s simulated); "
          f"wall {wall:.2f}s")
    print("messages:", ", ".join(f"{k}×{v}" for k, v in sorted(msgs.items())),
          f"| {trace.total_bytes()} wire bytes")


def run_batched(args, infos, rng) -> None:
    """The same QHB scenario with each epoch executed as one batched
    array-mode HoneyBadger epoch (TPU path)."""
    from hbbft_tpu.parallel.qhb import BatchedQueueingHoneyBadger

    n = args.nodes
    qhb = BatchedQueueingHoneyBadger(
        infos, batch_size=args.batch_size, cost_model=make_cost_model(args)
    )
    txs = gen_txs(args, rng)
    for i, tx in enumerate(txs):
        qhb.push(i % n, tx)

    print(f"\n{'epoch':>6} {'txs':>6} {'total':>6} {'wall(s)':>9}")
    t0 = time.perf_counter()
    last = [t0]

    def on_epoch(epoch, new):
        now = time.perf_counter()
        print(f"{epoch:>6} {len(new):>6} {len(qhb.committed):>6} "
              f"{now - last[0]:>9.2f}")
        last[0] = now

    # enough epochs for the workload even with worst-case sampling overlap
    max_epochs = max(64, 4 * -(-args.txs // max(n * args.batch_size, 1)))
    qhb.run_to_empty(rng, max_epochs=max_epochs, on_epoch=on_epoch)
    wall = time.perf_counter() - t0
    assert set(qhb.committed) == set(txs)
    print(f"\ncommitted {len(qhb.committed)}/{len(txs)} txs in "
          f"{qhb.epoch} batched epochs; wall {wall:.2f}s "
          f"({len(qhb.committed) / max(wall, 1e-9):.0f} tx/s incl. compile)")
    print_virtual_time(len(qhb.committed), qhb.virtual_time)


def run_batched_dynamic(args, infos, rng) -> None:
    """The composed stack: transaction queueing over dynamic membership —
    vote ``--remove-node`` out after the first epoch and drain the ledger
    across the DKG + era rotation."""
    from hbbft_tpu.parallel.qhb import BatchedQueueingDynamicHoneyBadger

    n = args.nodes
    victim = args.remove_node  # validated against 0..n-1 at arg parsing
    q = BatchedQueueingDynamicHoneyBadger(
        infos, batch_size=args.batch_size, rng=random.Random(args.seed + 1),
        cost_model=make_cost_model(args),
    )
    txs = gen_txs(args, rng)
    keepers = [nid for nid in range(n) if nid != victim]
    for i, tx in enumerate(txs):
        q.push(keepers[i % len(keepers)], tx)

    print(f"\n{'era.ep':>7} {'txs':>6} {'total':>6} {'validators':>11} "
          f"{'change':>12} {'wall(s)':>9}")
    t0 = time.perf_counter()
    last = t0
    epochs = 0
    max_epochs = max(
        64, 4 * -(-args.txs // max(n * args.batch_size, 1)) + 8
    )
    while q.pending() > 0 or q.dhb.era == 0:
        if epochs >= max_epochs:
            raise SystemExit("did not drain")
        if epochs == 1:
            for voter in list(q.dhb.validators):
                q.vote_to_remove(voter, victim)
            print(f"# epoch 1: all validators vote to remove {victim}")
        new = q.run_epoch(random.Random(3000 + epochs))
        b = q.dhb.batches[-1]
        now = time.perf_counter()
        print(f"{b.era:>4}.{b.epoch:<2} {len(new):>6} {len(q.committed):>6} "
              f"{len(q.dhb.validators):>11} {b.change.state:>12} "
              f"{now - last:>9.2f}")
        last = now
        epochs += 1
    wall = time.perf_counter() - t0
    assert set(q.committed) == set(txs)
    assert q.dhb.era >= 1 and victim not in q.dhb.validators
    print(f"\ncommitted {len(q.committed)}/{len(txs)} txs across the era "
          f"rotation in {epochs} epochs; era {q.dhb.era}, validators "
          f"{sorted(q.dhb.validators)}; wall {wall:.2f}s")
    print_virtual_time(len(q.committed), q.virtual_time)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Run a real multi-process localhost QHB cluster and pump load at it.

The networked sibling of ``examples/simulation.py``: instead of the
in-process simulator crank loop, this spawns ``--nodes`` OS processes
(``python -m hbbft_tpu.net.cluster``), each listening on
``base_port + node_id``, then drives ``--txs`` client transactions through
``--clients`` concurrent frontends and reports epochs/sec and end-to-end
submit→commit latency percentiles.

    python examples/cluster.py --nodes 4 --txs 200 --batch-size 8

Single-node mode (what the launcher spawns, also usable by hand across
machines sharing the same --seed):

    python -m hbbft_tpu.net.cluster --nodes 4 --node-id 0 \
        --seed 0 --base-port 24000
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from hbbft_tpu.net.client import ClusterClient, latency_percentiles
from hbbft_tpu.net.cluster import (
    ClusterConfig,
    connect_when_up,
    find_free_base_port,
    shutdown_procs,
    spawn_node,
)


async def run_load(cfg: ClusterConfig, txs: int, tx_size: int,
                   n_clients: int):
    clients = [
        await connect_when_up(cfg, c % cfg.n, client_id=f"load-{c}")
        for c in range(n_clients)
    ]
    t0 = time.monotonic()

    async def drive(ci: int, client: ClusterClient):
        for i in range(ci, txs, n_clients):
            tx = b"%08d:" % i + os.urandom(max(0, tx_size - 9))
            await client.submit(tx)
            await client.wait_committed(tx, timeout_s=120)

    await asyncio.gather(*(drive(ci, c) for ci, c in enumerate(clients)))
    wall = time.monotonic() - t0

    status = await clients[0].status()
    lat = latency_percentiles(l for c in clients for _d, l in c.latencies)
    print(f"\ncommitted {lat['count']} txs "
          f"in {status['batches']} epochs; wall {wall:.2f}s "
          f"({status['batches'] / wall:.1f} epochs/s, "
          f"{lat['count'] / wall:.0f} tx/s)")
    print(f"latency p50 {lat['p50_s'] * 1e3:.1f} ms | "
          f"p90 {lat['p90_s'] * 1e3:.1f} ms | "
          f"p99 {lat['p99_s'] * 1e3:.1f} ms")
    print(f"node 0 transport: {status['stats']}")
    for c in clients:
        await c.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txs", type=int, default=200)
    ap.add_argument("--tx-size", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, default=0,
                    help="0 → pick a free range automatically")
    ap.add_argument("--metrics-base-port", type=int, default=0,
                    help="obs endpoints (/metrics /status /spans "
                         "/flight) at base+i; 0 → auto (node ports + n); "
                         "-1 → off")
    ap.add_argument("--flight-dir", default="",
                    help="flight-recorder journal root (node i journals "
                         "to <dir>/node-i); empty → auto temp dir; "
                         "'off' → disable the recorder")
    ap.add_argument("--encrypt", action="store_true",
                    help="TPKE-encrypt contributions (EncryptionSchedule "
                         "always instead of never)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="epochs kept in flight per node (1 = sequential; "
                         "> 1 engages the epoch-pipelined scheduler)")
    ap.add_argument("--chaos", default="", metavar="SPEC",
                    help="apply a named link-shaping preset to every "
                         "node's egress (wan-100ms, lossy-1pct, "
                         "dup-reorder, partition-10s, bandwidth-64k) — "
                         "reproduce a campaign cell interactively")
    ap.add_argument("--chaos-seed", type=int, default=-1,
                    help="seed for the chaos fault RNGs (-1 = --seed); "
                         "pass a campaign cell's reported seed to replay "
                         "its fault schedule")
    args = ap.parse_args()
    if args.chaos:
        # validate the preset name before spawning anything
        from hbbft_tpu.chaos.link import preset_shape

        preset_shape(args.chaos, args.nodes)

    if args.base_port:
        base = args.base_port
        metrics_base = args.metrics_base_port or base + args.nodes
    else:
        # one contiguous free range covers both: node ports in the first
        # half, obs endpoints in the second
        base = find_free_base_port(2 * args.nodes)
        metrics_base = args.metrics_base_port or base + args.nodes
    if args.metrics_base_port == -1:
        metrics_base = 0
    # flight recorder on by default: every run leaves an auditable
    # black-box journal behind
    if args.flight_dir == "off":
        flight_dir = ""
    elif args.flight_dir:
        flight_dir = args.flight_dir
    else:
        import tempfile

        flight_dir = tempfile.mkdtemp(prefix="hbbft-flight-")
    cfg = ClusterConfig(
        n=args.nodes, seed=args.seed, base_port=base,
        metrics_base_port=metrics_base,
        batch_size=args.batch_size, encrypt=args.encrypt,
        flight_dir=flight_dir, pipeline_depth=args.pipeline_depth,
        chaos=args.chaos, chaos_seed=args.chaos_seed,
    )
    print(f"spawning {cfg.n} node processes on "
          f"{cfg.host}:{cfg.base_port}..{cfg.base_port + cfg.n - 1}…")
    if cfg.chaos:
        seed = cfg.seed if cfg.chaos_seed < 0 else cfg.chaos_seed
        print(f"chaos preset {cfg.chaos!r} active on every link "
              f"(fault seed {seed}) — expect shaped latency/faults; "
              f"shaping counters are on each node's /metrics "
              f"(hbbft_chaos_*)")
    if metrics_base:
        print(f"obs endpoints: http://{cfg.host}:{metrics_base}.."
              f"{metrics_base + cfg.n - 1}/metrics — watch live with\n"
              f"    python -m hbbft_tpu.obs.top "
              f"--base-port {metrics_base} --nodes {cfg.n}")
    if flight_dir:
        print(f"flight journals: {flight_dir} — audit offline with\n"
              f"    python -m hbbft_tpu.obs.audit {flight_dir}")
    procs = {nid: spawn_node(cfg, nid) for nid in range(cfg.n)}

    async def session():
        # connect_when_up retries per node, so the load clients double as
        # the cluster-is-up barrier
        print("cluster spawning; pumping load once nodes accept…")
        await run_load(cfg, args.txs, args.tx_size, args.clients)

    try:
        asyncio.run(session())
    finally:
        shutdown_procs(procs.values())


if __name__ == "__main__":
    main()

"""Measure the OBJECT-MODE (host) full-TPKE HoneyBadger epoch at N=64 f=21.

One-shot evidence run for the round-5 verdict ask: replace the N^3
extrapolation behind `hb_epoch64`'s vs_baseline with a measurement.  The
result is recorded in BASELINE_MEASURED.json (committed) and bench.py reads
it for the measured baseline row.  Run it on an otherwise idle box:

    python tools_measure_host64.py
"""
import json, os, random, sys, time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax

jax.config.update("jax_platforms", "cpu")

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.honey_badger import Batch, EncryptionSchedule, HoneyBadger
from hbbft_tpu.sim import NetBuilder, NullAdversary

N, F, TX = 64, 21, 256

t0 = time.perf_counter()
infos = NetworkInfo.generate_map(list(range(N)), random.Random(5))
t_keys = time.perf_counter() - t0
print(f"# keygen: {t_keys:.1f}s", file=sys.stderr, flush=True)

rng = random.Random(23)
contribs = {
    i: bytes(rng.randrange(256) for _ in range(TX)) for i in range(N)
}
net = NetBuilder(list(range(N))).adversary(NullAdversary()).message_limit(
    100_000_000
).crank_limit(100_000_000).using_step(
    lambda nid: HoneyBadger.builder(infos[nid])
    .session_id(b"hb-epoch64-host")
    .encryption_schedule(EncryptionSchedule.always())
    .rng(random.Random(200 + nid))
    .build()
)
t0 = time.perf_counter()
for nid in net.node_ids():
    net.send_input(nid, contribs[nid])
net.run_to_quiescence()
t_epoch = time.perf_counter() - t0
for nid in net.node_ids():
    assert any(isinstance(o, Batch) for o in net.nodes[nid].outputs), nid
print(f"# epoch: {t_epoch:.1f}s, {net.messages_delivered} msgs",
      file=sys.stderr, flush=True)

out = {
    "metric": "hb_epoch64_host_measured",
    "t_epoch_s": round(t_epoch, 1),
    "messages_delivered": net.messages_delivered,
    "shape": f"N={N} f={F} tx={TX}B",
    "notes": "object-mode VirtualNet, NullAdversary, full TPKE, "
             "endomorphism-accelerated native oracle (round 5); "
             "single CPU core",
    "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
}
path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "BASELINE_MEASURED.json")
data = {}
if os.path.exists(path):
    with open(path) as fh:
        data = json.load(fh)
data["hb_epoch64_host"] = out
# atomic replace: a kill mid-write must not truncate the committed record
tmp = path + ".tmp"
with open(tmp, "w") as fh:
    json.dump(data, fh, indent=1)
os.replace(tmp, path)
print(json.dumps(out))
